"""Named collective wrappers for shard_map kernels.

TPU-native replacement for the reference's absent NCCL/MPI layer
(SURVEY.md §5.8): all hot-path tensor exchange is XLA collectives compiled
over ICI/DCN. Inside ``jax.jit`` GSPMD inserts these automatically from
shardings; these explicit wrappers are for ``shard_map`` kernels (ring
attention KV rotation, Ulysses all-to-all, MoE dispatch — and the
overlapped gradient-accumulation step's :func:`bucketed_psum`, whose
byte-bounded buckets are what lets XLA's async collectives pipeline a
gradient all-reduce behind the next microbatch's backward; see
docs/performance.md "Overlapped training") where the communication
schedule is the algorithm.
"""

from __future__ import annotations

from typing import Any, List, Sequence, Union

from jax import lax

from unionml_tpu.parallel import compat

AxisName = Union[str, Sequence[str]]

#: Default all-reduce bucket size for :func:`bucketed_psum`. Big enough
#: that per-collective launch overhead amortizes, small enough that the
#: first bucket's all-reduce can start while later buckets' grads are
#: still being produced/scheduled (the classic DDP bucketing trade-off).
DEFAULT_PSUM_BUCKET_BYTES = 4 << 20


def psum(x: Any, axis: AxisName):
    """Sum-reduce across an axis (gradient reduction on the data axis)."""
    return lax.psum(x, axis)


def bucketed_psum(
    tree: Any,
    axis: AxisName,
    *,
    bucket_bytes: int = DEFAULT_PSUM_BUCKET_BYTES,
) -> Any:
    """``lax.psum(tree, axis)`` issued as one collective per byte-bounded
    bucket of leaves instead of one monolithic collective.

    Values are bitwise identical to the un-bucketed psum — bucketing
    only changes how many all-reduce ops XLA sees, never which shards
    reduce together — but the chunking is what makes latency hiding
    work: a single whole-gradient all-reduce can only start once every
    leaf is ready and must finish before ANY consumer runs, while
    per-bucket collectives start as their leaves close and overlap
    each other (and, in the deferred-accumulation step, the next
    microbatch's backward). Leaves above ``bucket_bytes`` get their own
    bucket — a tensor is never split. Only callable inside
    ``shard_map``/``pmap`` where ``axis`` is bound.
    """
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be > 0, got {bucket_bytes}")
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    buckets: List[List[int]] = []
    sizes: List[int] = []
    for i, leaf in enumerate(leaves):
        nbytes = int(getattr(leaf, "size", 1)) * int(
            getattr(getattr(leaf, "dtype", None), "itemsize", 4)
        )
        if buckets and sizes[-1] + nbytes <= bucket_bytes:
            buckets[-1].append(i)
            sizes[-1] += nbytes
        else:
            buckets.append([i])
            sizes.append(nbytes)
    reduced: List[Any] = [None] * len(leaves)
    for bucket in buckets:
        out = lax.psum([leaves[i] for i in bucket], axis)
        for i, val in zip(bucket, out):
            reduced[i] = val
    return jax.tree_util.tree_unflatten(treedef, reduced)


def pmean(x: Any, axis: AxisName):
    return lax.pmean(x, axis)


def all_gather(x: Any, axis: AxisName, *, gather_axis: int = 0, tiled: bool = True):
    """Gather shards along ``gather_axis`` (fsdp param gather)."""
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x: Any, axis: AxisName, *, scatter_axis: int = 0):
    """Sum-reduce then scatter along ``scatter_axis`` (fsdp grad shard)."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def ppermute_shift(x: Any, axis: str, *, shift: int = 1):
    """Rotate shards around a ring (ring-attention KV rotation over ICI)."""
    n = compat.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def all_to_all(x: Any, axis: str, *, split_axis: int, concat_axis: int, tiled: bool = True):
    """Transpose sharding between two tensor dims (Ulysses head↔sequence
    reshuffle, MoE token dispatch)."""
    return lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return compat.axis_size(axis)
