"""Named collective wrappers for shard_map kernels.

TPU-native replacement for the reference's absent NCCL/MPI layer
(SURVEY.md §5.8): all hot-path tensor exchange is XLA collectives compiled
over ICI/DCN. Inside ``jax.jit`` GSPMD inserts these automatically from
shardings; these explicit wrappers are for ``shard_map`` kernels (ring
attention KV rotation, Ulysses all-to-all, MoE dispatch) where the
communication schedule is the algorithm.
"""

from __future__ import annotations

from typing import Any, Sequence, Union

from jax import lax

AxisName = Union[str, Sequence[str]]


def psum(x: Any, axis: AxisName):
    """Sum-reduce across an axis (gradient reduction on the data axis)."""
    return lax.psum(x, axis)


def pmean(x: Any, axis: AxisName):
    return lax.pmean(x, axis)


def all_gather(x: Any, axis: AxisName, *, gather_axis: int = 0, tiled: bool = True):
    """Gather shards along ``gather_axis`` (fsdp param gather)."""
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x: Any, axis: AxisName, *, scatter_axis: int = 0):
    """Sum-reduce then scatter along ``scatter_axis`` (fsdp grad shard)."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def ppermute_shift(x: Any, axis: str, *, shift: int = 1):
    """Rotate shards around a ring (ring-attention KV rotation over ICI)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def all_to_all(x: Any, axis: str, *, split_axis: int, concat_axis: int, tiled: bool = True):
    """Transpose sharding between two tensor dims (Ulysses head↔sequence
    reshuffle, MoE token dispatch)."""
    return lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return lax.axis_size(axis)
