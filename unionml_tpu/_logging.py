"""Framework logger (reference: unionml/_logging.py:3-7), env-tunable.

- ``UNIONML_TPU_LOG_LEVEL`` — level name (``DEBUG``/``INFO``/...; default
  ``INFO``); unknown names fall back to ``INFO`` instead of crashing at
  import.
- ``UNIONML_TPU_LOG_JSON=1`` — one JSON object per line (``ts``,
  ``level``, ``logger``, ``msg``[, ``exc``]) so engine/batcher error
  logs are machine-parseable alongside the :mod:`unionml_tpu.telemetry`
  metrics and trace exports.

Handler registration is guarded so a re-import (tests reloading the
module, notebooks) cannot double-emit every line.
"""

import json
import logging
import os


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


def _configure(logger: logging.Logger) -> None:
    level_name = os.environ.get("UNIONML_TPU_LOG_LEVEL", "INFO").upper()
    level = logging.getLevelName(level_name)
    logger.setLevel(level if isinstance(level, int) else logging.INFO)
    if not logger.handlers:  # re-import must not stack handlers
        handler = logging.StreamHandler()
        if os.environ.get("UNIONML_TPU_LOG_JSON") == "1":
            handler.setFormatter(_JsonFormatter())
        else:
            handler.setFormatter(logging.Formatter("[unionml-tpu] %(message)s"))
        logger.addHandler(handler)
    logger.propagate = False


logger = logging.getLogger("unionml_tpu")
_configure(logger)
