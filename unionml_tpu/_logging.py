"""Framework logger (reference: unionml/_logging.py:3-7)."""

import logging

logger = logging.getLogger("unionml_tpu")
logger.setLevel(logging.INFO)

_handler = logging.StreamHandler()
_handler.setFormatter(logging.Formatter("[unionml-tpu] %(message)s"))
logger.addHandler(_handler)
logger.propagate = False
