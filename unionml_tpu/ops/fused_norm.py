"""Fused LayerNorm / RMSNorm Pallas kernels (+ residual-add variant).

Why a kernel at all: the ViT-B step decomposition (BASELINE.md "Where
the remaining gap lives") put ~22 ms of the 53.8 ms step in VPU
elementwise work — LayerNorm among the biggest bandwidth consumers.
XLA's LayerNorm is already a fused reduce+normalize, but its BACKWARD
materializes the saved mean/rstd and runs separate reduction passes for
dgamma/dbeta and dx; this kernel pair instead:

- forward: one pass over a row block — fp32 statistics, normalize,
  scale/shift, cast — with NO saved statistics (round-2 Pallas lesson:
  writing small per-row stats forces lane-major relayouts that cost
  more than recomputing the reductions in the backward);
- backward: one pass recomputes the statistics from x and produces dx
  plus PER-BLOCK partial dgamma/dbeta rows ([grid, D], summed in fp32
  outside the kernel — a [G, D] tree-sum is one cheap XLA reduce);
- the ``*_add_*`` variants fuse the transformer residual add
  (``s = x + r; y = norm(s)``) into the same pass, saving one full
  [rows, D] HBM round trip per block in both directions.

Layout: inputs flatten to [rows, D]; D must be a multiple of 128
(lane width). Row blocks of 256 keep bf16 tiles aligned (16-sublane
multiples) and fit VMEM with room for the fp32 intermediates.

No reference counterpart — the reference has no kernels (SURVEY.md §2:
"100% Python, no native components").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_BLOCK_ROWS = 256


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _stats(x32, *, rms: bool, eps: float):
    if rms:
        mu = 0.0
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    else:
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    return mu, jax.lax.rsqrt(var + eps)


# --------------------------------------------------------------------- #
# kernels
# --------------------------------------------------------------------- #


def _valid_rows(block: int, rows: int):
    """Row-validity column for the current grid block, or None when the
    grid divides evenly. The trailing block reads padding garbage —
    harmless for per-row outputs (out-of-bounds writes are dropped) but
    it MUST be zeroed out of cross-row dgamma/dbeta sums, and zeroed on
    input so a garbage row's NaN stats can't poison 0*NaN."""
    if rows % block == 0:
        return None
    start = pl.program_id(0) * block
    idx = start + jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)
    return idx < rows


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, *, eps, rms, rows):
    x32 = x_ref[...].astype(jnp.float32)
    valid = _valid_rows(x_ref.shape[0], rows)
    if valid is not None:
        x32 = jnp.where(valid, x32, 0.0)
    mu, rstd = _stats(x32, rms=rms, eps=eps)
    xhat = (x32 - mu) * rstd
    out = xhat * g_ref[...].astype(jnp.float32)
    if b_ref is not None:
        out = out + b_ref[...].astype(jnp.float32)
    y_ref[...] = out.astype(y_ref.dtype)


def _add_fwd_kernel(x_ref, r_ref, g_ref, b_ref, s_ref, y_ref, *, eps, rms, rows):
    s32 = x_ref[...].astype(jnp.float32) + r_ref[...].astype(jnp.float32)
    valid = _valid_rows(x_ref.shape[0], rows)
    if valid is not None:
        s32 = jnp.where(valid, s32, 0.0)
    s_ref[...] = s32.astype(s_ref.dtype)
    mu, rstd = _stats(s32, rms=rms, eps=eps)
    xhat = (s32 - mu) * rstd
    out = xhat * g_ref[...].astype(jnp.float32)
    if b_ref is not None:
        out = out + b_ref[...].astype(jnp.float32)
    y_ref[...] = out.astype(y_ref.dtype)


def _bwd_kernel(x_ref, g_ref, dy_ref, dx_ref, dg_ref, db_ref, *, eps, rms, rows):
    """Recompute stats, emit dx and this block's dgamma/dbeta partials.

    dx = rstd * (dyg - mean(dyg) - xhat * mean(dyg * xhat))   (LayerNorm)
    dx = rstd * (dyg - xhat * mean(dyg * xhat))               (RMSNorm)
    where dyg = dy * gamma. dgamma = sum(dy * xhat); dbeta = sum(dy).
    """
    x32 = x_ref[...].astype(jnp.float32)
    dy32 = dy_ref[...].astype(jnp.float32)
    valid = _valid_rows(x_ref.shape[0], rows)
    if valid is not None:
        x32 = jnp.where(valid, x32, 0.0)
        dy32 = jnp.where(valid, dy32, 0.0)
    mu, rstd = _stats(x32, rms=rms, eps=eps)
    xhat = (x32 - mu) * rstd
    dyg = dy32 * g_ref[...].astype(jnp.float32)
    c2 = jnp.mean(dyg * xhat, axis=-1, keepdims=True)
    if rms:
        dx = rstd * (dyg - xhat * c2)
    else:
        c1 = jnp.mean(dyg, axis=-1, keepdims=True)
        dx = rstd * (dyg - c1 - xhat * c2)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    # partials are written as (8, D) tiles (TPU min sublane count): the
    # sum in row 0, zero elsewhere — the outer fp32 reduce over ALL rows
    # absorbs the zeros for free
    pad7 = ((0, 7), (0, 0))
    dg_ref[...] = jnp.pad(jnp.sum(dy32 * xhat, axis=0, keepdims=True), pad7)
    if db_ref is not None:
        db_ref[...] = jnp.pad(jnp.sum(dy32, axis=0, keepdims=True), pad7)


# --------------------------------------------------------------------- #
# pallas_call wrappers over [rows, D]
# --------------------------------------------------------------------- #


def _row_grid(rows: int):
    block = min(_BLOCK_ROWS, rows)
    # ceil grid: the trailing partial block is masked inside the kernels
    return pl.cdiv(rows, block), block


def _check_lanes(d: int) -> None:
    """Mosaic requires the last dim to tile 128 lanes; fail with a clear
    message instead of a lowering error deep inside pallas_call (CPU
    interpret mode has no lane layout and accepts any width — the tiny
    test configs rely on that)."""
    if d % 128 and not _interpret():
        raise ValueError(
            f"fused norm requires the feature dim to be a multiple of 128 "
            f"(TPU lane width), got {d}; use the xla norm impl for this "
            "model size"
        )


def _norm_fwd(x, gamma, beta, *, eps, rms):
    rows, d = x.shape
    _check_lanes(d)
    grid, block = _row_grid(rows)
    row_spec = pl.BlockSpec((block, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    args = [x, gamma[None, :]]
    in_specs = [row_spec, vec_spec]
    if beta is not None:
        args.append(beta[None, :])
        in_specs.append(vec_spec)
        kernel = functools.partial(_fwd_kernel, eps=eps, rms=rms, rows=rows)
    else:
        kernel = functools.partial(
            lambda x_ref, g_ref, y_ref, **kw: _fwd_kernel(
                x_ref, g_ref, None, y_ref, **kw
            ),
            eps=eps, rms=rms, rows=rows,
        )
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=_interpret(),
    )(*args)


def _norm_add_fwd(x, r, gamma, beta, *, eps, rms):
    rows, d = x.shape
    _check_lanes(d)
    grid, block = _row_grid(rows)
    row_spec = pl.BlockSpec((block, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    args = [x, r, gamma[None, :]]
    in_specs = [row_spec, row_spec, vec_spec]
    if beta is not None:
        args.append(beta[None, :])
        in_specs.append(vec_spec)
        kernel = functools.partial(_add_fwd_kernel, eps=eps, rms=rms, rows=rows)
    else:
        kernel = functools.partial(
            lambda x_ref, r_ref, g_ref, s_ref, y_ref, **kw: _add_fwd_kernel(
                x_ref, r_ref, g_ref, None, s_ref, y_ref, **kw
            ),
            eps=eps, rms=rms, rows=rows,
        )
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=[row_spec, row_spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, d), x.dtype),
            jax.ShapeDtypeStruct((rows, d), x.dtype),
        ],
        interpret=_interpret(),
    )(*args)


def _norm_bwd(x, gamma, dy, *, eps, rms, with_beta):
    rows, d = x.shape
    _check_lanes(d)
    grid, block = _row_grid(rows)
    row_spec = pl.BlockSpec((block, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    part_spec = pl.BlockSpec((8, d), lambda i: (i, 0))
    out_specs = [row_spec, part_spec]
    out_shape = [
        jax.ShapeDtypeStruct((rows, d), x.dtype),
        jax.ShapeDtypeStruct((grid * 8, d), jnp.float32),
    ]
    if with_beta:
        kernel = functools.partial(_bwd_kernel, eps=eps, rms=rms, rows=rows)
        out_specs.append(part_spec)
        out_shape.append(jax.ShapeDtypeStruct((grid * 8, d), jnp.float32))
    else:
        kernel = functools.partial(
            lambda x_ref, g_ref, dy_ref, dx_ref, dg_ref, **kw: _bwd_kernel(
                x_ref, g_ref, dy_ref, dx_ref, dg_ref, None, **kw
            ),
            eps=eps, rms=rms, rows=rows,
        )
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[row_spec, vec_spec, row_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret(),
    )(x, gamma[None, :], dy)
    dx, dg_parts = outs[0], outs[1]
    dgamma = dg_parts.sum(axis=0)
    dbeta = outs[2].sum(axis=0) if with_beta else None
    return dx, dgamma, dbeta


# --------------------------------------------------------------------- #
# public ops (custom_vjp; arbitrary leading dims)
# --------------------------------------------------------------------- #


def _flatten(x):
    return x.reshape((-1, x.shape[-1])), x.shape


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_layer_norm(x, gamma, beta, eps: float = 1e-6, rms: bool = False):
    """``layer_norm(x) * gamma + beta`` over the last axis, one fused
    pass each way. ``rms=True`` drops mean subtraction and ``beta``
    (pass ``beta=None``) — Llama-style RMSNorm."""
    x2, shape = _flatten(x)
    return _norm_fwd(x2, gamma, beta, eps=eps, rms=rms).reshape(shape)


def _fln_fwd(x, gamma, beta, eps, rms):
    return fused_layer_norm(x, gamma, beta, eps, rms), (x, gamma)


def _fln_bwd(eps, rms, res, dy):
    x, gamma = res
    x2, shape = _flatten(x)
    dy2, _ = _flatten(dy)
    dx, dgamma, dbeta = _norm_bwd(
        x2, gamma, dy2, eps=eps, rms=rms, with_beta=not rms
    )
    return dx.reshape(shape), dgamma, dbeta


fused_layer_norm.defvjp(_fln_fwd, _fln_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_add_layer_norm(x, r, gamma, beta, eps: float = 1e-6, rms: bool = False):
    """``s = x + r; y = norm(s)`` in one pass; returns ``(s, y)``.

    The transformer-block pattern ``s = residual + branch; h = norm(s)``
    re-reads ``s`` immediately — fusing the add saves one [rows, D] HBM
    round trip each way. The backward folds the norm's ds into the
    incoming residual gradient, so ``ds_total`` flows to BOTH x and r.
    """
    x2, shape = _flatten(x)
    r2, _ = _flatten(r)
    s, y = _norm_add_fwd(x2, r2, gamma, beta, eps=eps, rms=rms)
    return s.reshape(shape), y.reshape(shape)


def _faln_fwd(x, r, gamma, beta, eps, rms):
    s, y = fused_add_layer_norm(x, r, gamma, beta, eps, rms)
    return (s, y), (s, gamma)


def _faln_bwd(eps, rms, res, grads):
    s, gamma = res
    ds_in, dy = grads
    s2, shape = _flatten(s)
    dy2, _ = _flatten(dy)
    dx, dgamma, dbeta = _norm_bwd(
        s2, gamma, dy2, eps=eps, rms=rms, with_beta=not rms
    )
    ds_total = dx.reshape(shape) + ds_in
    return ds_total, ds_total, dgamma, dbeta


fused_add_layer_norm.defvjp(_faln_fwd, _faln_bwd)


def fused_rms_norm(x, scale, eps: float = 1e-5):
    """Llama-style RMSNorm through the fused kernel pair."""
    return fused_layer_norm(x, scale, None, eps, True)
