"""TPU compute ops: attention family, MoE dispatch, fused kernels.

No reference counterpart (the reference is an orchestration framework; its
FLOPs live in sklearn/torch — SURVEY.md §2). These ops are the hot-path
kernels of the TPU-native model zoo:

- :mod:`unionml_tpu.ops.attention` — XLA multi-head attention (GQA-aware)
  + memory-efficient blockwise attention (online softmax over KV blocks).
- :mod:`unionml_tpu.ops.flash_attention` — Pallas TPU flash-attention
  kernel (VMEM-tiled, MXU-shaped, causal block skipping).
- :mod:`unionml_tpu.ops.ring_attention` — sequence-parallel attention via
  shard_map + ppermute KV rotation over ICI.
- :mod:`unionml_tpu.ops.ulysses` — all-to-all head<->sequence reshuffle
  sequence parallelism.
- :mod:`unionml_tpu.ops.moe` — mixture-of-experts routing + expert-parallel
  dispatch.
"""

from unionml_tpu.ops.attention import attention, blockwise_attention, mha_reference
from unionml_tpu.ops.moe import (
    MoEMlp,
    expert_capacity,
    expert_parallel_moe,
    expert_parallel_moe_sharded,
    make_dispatch,
    migrate_moe_router_params,
    top_k_routing,
)

__all__ = [
    "attention", "blockwise_attention", "mha_reference",
    "MoEMlp", "top_k_routing", "make_dispatch", "expert_capacity",
    "expert_parallel_moe", "expert_parallel_moe_sharded",
    "migrate_moe_router_params",
]
