"""Ulysses sequence parallelism: all-to-all head<->sequence reshuffle.

The alternative long-context strategy to ring attention (SURVEY.md §5.7):
instead of rotating K/V shards, one ``all_to_all`` re-shards
sequence-sharded activations into head-sharded ones, every device runs
*full-sequence* attention over its subset of heads (any local impl —
XLA, flash), and a second all_to_all restores sequence sharding. Two
collectives total per attention call, both riding ICI; requires
``num_heads % axis_size == 0``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax.numpy as jnp

from unionml_tpu.parallel import compat
from jax import lax


def _seq_to_heads(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """[B, S/n, H, D] -> [B, S, H/n, D] via all_to_all."""
    # split the head axis across devices, concat the sequence axis
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def _heads_to_seq(x: jnp.ndarray, axis: str) -> jnp.ndarray:
    """[B, S, H/n, D] -> [B, S/n, H, D] via all_to_all."""
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis: str = "sequence",
    causal: bool = False,
    impl: str = "xla",
    scale: Optional[float] = None,
    block_size: int = 512,
) -> jnp.ndarray:
    """Per-shard Ulysses body (call inside shard_map).

    Local shards are [B, S/n, H, D]; K/V may have fewer (GQA) heads but
    they must still divide the axis size.
    """
    from unionml_tpu.ops.attention import attention

    n = compat.axis_size(axis)
    for name, t in (("q", q), ("k", k), ("v", v)):
        if t.shape[2] % n:
            raise ValueError(
                f"ulysses requires {name} heads ({t.shape[2]}) divisible by "
                f"axis size ({n})"
            )
    q_full = _seq_to_heads(q, axis)
    k_full = _seq_to_heads(k, axis)
    v_full = _seq_to_heads(v, axis)
    out = attention(
        q_full, k_full, v_full, causal=causal, impl=impl, scale=scale,
        block_size=block_size,
    )
    return _heads_to_seq(out, axis)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh,
    *,
    axis: str = "sequence",
    causal: bool = False,
    impl: str = "xla",
    scale: Optional[float] = None,
    block_size: int = 512,
) -> jnp.ndarray:
    """Ulysses attention over globally-shaped [B,S,H,D] tensors."""
    from unionml_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis, None, None)
    body = functools.partial(
        ulysses_attention_sharded, axis=axis, causal=causal, impl=impl,
        scale=scale, block_size=block_size,
    )
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
