"""Mixture-of-experts: top-k routing + expert-parallel dispatch.

Expert parallelism (SURVEY.md §2.4 TPU additions): the expert dimension of
the MLP weights is sharded over the mesh's ``expert`` axis. The dense
einsum dispatch below keeps every tensor static-shaped (no gather/scatter
with data-dependent shapes — XLA-friendly), and under pjit the one-hot
combine einsums compile to ``all_to_all``-style collectives on the expert
axis. Aux losses follow the standard load-balancing recipe (mean gate
fraction x mean routing fraction per expert).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


def top_k_routing(
    gate_logits: jnp.ndarray, num_selected: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Softmax-normalized top-k routing.

    gate_logits: [tokens, experts]. Returns (weights [T, k],
    indices [T, k], aux_loss scalar).
    """
    num_experts = gate_logits.shape[-1]
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    weights, indices = jax.lax.top_k(probs, num_selected)
    weights = weights / jnp.maximum(weights.sum(-1, keepdims=True), 1e-9)

    # load-balancing aux loss (Switch-style)
    routing_fraction = jnp.mean(
        jax.nn.one_hot(indices[..., 0], num_experts, dtype=jnp.float32), axis=0
    )
    gate_fraction = jnp.mean(probs, axis=0)
    aux_loss = num_experts * jnp.sum(routing_fraction * gate_fraction)
    return weights.astype(gate_logits.dtype), indices, aux_loss


class MoEMlp(nn.Module):
    """Expert-parallel SwiGLU MLP block.

    Weight shapes carry a leading expert dim — shard it with a
    ``PartitionRule(r"moe/.*", ("expert", ...))`` to get expert parallelism
    on the mesh.
    """

    num_experts: int
    num_selected: int
    hidden_dim: int
    model_dim: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """x: [batch, seq, model_dim] -> (out, aux_loss)."""
        b, s, d = x.shape
        tokens = x.reshape(b * s, d)

        gate_logits = nn.Dense(self.num_experts, use_bias=False, dtype=self.dtype,
                               name="router")(tokens)
        weights, indices, aux_loss = top_k_routing(gate_logits, self.num_selected)

        w_gate = self.param(
            "w_gate", nn.initializers.lecun_normal(),
            (self.num_experts, d, self.hidden_dim), self.dtype,
        )
        w_up = self.param(
            "w_up", nn.initializers.lecun_normal(),
            (self.num_experts, d, self.hidden_dim), self.dtype,
        )
        w_down = self.param(
            "w_down", nn.initializers.lecun_normal(),
            (self.num_experts, self.hidden_dim, d), self.dtype,
        )

        # dense one-hot dispatch: static shapes, collectives inserted by
        # GSPMD when the expert dim is sharded
        dispatch = jax.nn.one_hot(indices, self.num_experts, dtype=self.dtype)
        # [T, k, E] x [T, d] -> per-expert token batches [E, T, d] weighted later
        combine = jnp.einsum("tke,tk->te", dispatch, weights.astype(self.dtype))

        mask = (combine > 0).astype(self.dtype)
        expert_in = jnp.einsum("te,td->etd", mask, tokens.astype(self.dtype))
        gated = jax.nn.silu(jnp.einsum("etd,edh->eth", expert_in, w_gate))
        up = jnp.einsum("etd,edh->eth", expert_in, w_up)
        expert_out = jnp.einsum("eth,ehd->etd", gated * up, w_down)
        out = jnp.einsum("etd,te->td", expert_out, combine)
        return out.reshape(b, s, d).astype(self.dtype), aux_loss
