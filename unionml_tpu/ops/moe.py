"""Mixture-of-experts: top-k routing + expert-parallel dispatch.

Expert parallelism (SURVEY.md §2.4 TPU additions, §7.8 "EP: expert-sharded
MoE with all_to_all dispatch"). Two dispatch paths, one routing math:

- **Dense einsum dispatch** (:class:`MoEMlp`): every tensor is
  static-shaped; with the expert dim of the weights sharded over the
  mesh's ``expert`` axis, GSPMD inserts the all_to_all-style collectives.
  No capacity limit — every routed token is processed. The default for
  pjit training via partition rules.
- **Explicit all_to_all dispatch**
  (:func:`expert_parallel_moe_sharded` / :func:`expert_parallel_moe`):
  the GShard/Switch algorithm inside ``shard_map`` — tokens are bucketed
  per expert up to a static ``capacity``, buckets ride one
  ``lax.all_to_all`` over the ``expert`` axis to the expert-owning
  device, the expert MLP runs on its local shard, and a reverse
  all_to_all + combine-weighted sum scatters results back. Differentiable
  (all_to_all transposes to the reverse all_to_all).

Aux losses follow the standard load-balancing recipe (mean gate fraction
x mean routing fraction per expert).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from unionml_tpu.parallel import compat
from flax import linen as nn
from jax import lax


def load_balance_stats(
    probs: jnp.ndarray, indices: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-expert token-MEAN stats behind the load-balance loss.

    probs: [tokens, experts] router softmax; indices: [tokens, k].
    Returns ``(routing_fraction [E], gate_fraction [E])``. The aux loss is
    ``E * sum(rf * gf)`` — both serial (:func:`top_k_routing`) and
    sequence-parallel (models/sequence_parallel.py, which pmeans the
    fractions across shards first) form it from THIS function, so the
    two training paths cannot drift apart.
    """
    num_experts = probs.shape[-1]
    routing_fraction = jnp.mean(
        jax.nn.one_hot(indices[..., 0], num_experts, dtype=jnp.float32), axis=0
    )
    gate_fraction = jnp.mean(probs.astype(jnp.float32), axis=0)
    return routing_fraction, gate_fraction


def top_k_routing(
    gate_logits: jnp.ndarray, num_selected: int, *, return_stats: bool = False
):
    """Softmax-normalized top-k routing.

    gate_logits: [tokens, experts]. Returns (weights [T, k],
    indices [T, k], aux_loss scalar) — plus the
    ``(routing_fraction, gate_fraction)`` pair behind the aux loss when
    ``return_stats`` (so callers that need the raw fractions, e.g. the
    sequence-parallel sow, don't recompute them).
    """
    num_experts = gate_logits.shape[-1]
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    weights, indices = jax.lax.top_k(probs, num_selected)
    weights = weights / jnp.maximum(weights.sum(-1, keepdims=True), 1e-9)

    # load-balancing aux loss (Switch-style)
    routing_fraction, gate_fraction = load_balance_stats(probs, indices)
    aux_loss = num_experts * jnp.sum(routing_fraction * gate_fraction)
    weights = weights.astype(gate_logits.dtype)
    if return_stats:
        return weights, indices, aux_loss, (routing_fraction, gate_fraction)
    return weights, indices, aux_loss


def expert_capacity(
    tokens: int, num_experts: int, num_selected: int, capacity_factor: float
) -> int:
    """Static per-expert token bucket size for capacity-based dispatch."""
    return max(1, int(math.ceil(num_selected * tokens * capacity_factor / num_experts)))


def make_dispatch(
    gate_logits: jnp.ndarray, num_selected: int, capacity: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Capacity-bucketed dispatch/combine tensors (GShard tokens-choose).

    gate_logits: [T, E]. Returns float32 ``(dispatch [T, E, C],
    combine [T, E, C], aux_loss)``: ``dispatch[t, e, c] == 1`` iff token t
    occupies slot c of expert e's bucket; ``combine`` carries the routing
    weight in the same slot. Priority is choice-major (every token's 1st
    choice is bucketed before any 2nd choice), position within a choice is
    token order; overflow beyond ``capacity`` is dropped.
    """
    tokens, num_experts = gate_logits.shape
    weights, indices, aux_loss = top_k_routing(gate_logits, num_selected)

    onehot = jax.nn.one_hot(indices, num_experts, dtype=jnp.int32)  # [T, k, E]
    # choice-major flattening so 1st choices win bucket slots
    flat = onehot.transpose(1, 0, 2).reshape(num_selected * tokens, num_experts)
    position = jnp.cumsum(flat, axis=0) - flat  # slot index within each expert
    keep = (position < capacity) & (flat > 0)
    slot = jax.nn.one_hot(position, capacity, dtype=jnp.float32)  # [kT, E, C]
    slotted = keep[..., None].astype(jnp.float32) * slot
    slotted = slotted.reshape(num_selected, tokens, num_experts, capacity)
    slotted = slotted.transpose(1, 0, 2, 3)  # [T, k, E, C]
    dispatch = slotted.sum(axis=1)
    combine = (slotted * weights.astype(jnp.float32)[:, :, None, None]).sum(axis=1)
    return dispatch, combine, aux_loss


def _swiglu_experts(x, w_gate, w_up, w_down):
    """x: [E, C, d]; w_*: [E, d, h] / [E, h, d] -> [E, C, d]."""
    gated = jax.nn.silu(jnp.einsum("ecd,edh->ech", x, w_gate))
    up = jnp.einsum("ecd,edh->ech", x, w_up)
    return jnp.einsum("ech,ehd->ecd", gated * up, w_down)


def expert_parallel_moe_sharded(
    x: jnp.ndarray,
    router_kernel: jnp.ndarray,
    w_gate: jnp.ndarray,
    w_up: jnp.ndarray,
    w_down: jnp.ndarray,
    *,
    axis: str = "expert",
    num_selected: int = 2,
    capacity_factor: float = 2.0,
    capacity: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-shard expert-parallel MoE body (call inside shard_map).

    ``x``: local token shard [T_local, d]; ``router_kernel``: replicated
    [d, E_global]; ``w_gate/w_up/w_down``: local expert shards
    [E_local, ...] with E_global = axis_size * E_local. Returns the local
    output shard [T_local, d] and the group-mean aux loss (replicated).
    """
    ep = compat.axis_size(axis)
    t_local, d = x.shape
    e_global = router_kernel.shape[-1]
    assert w_gate.shape[0] * ep == e_global, (
        f"expert weights shard {w_gate.shape[0]} x axis {ep} != {e_global} experts"
    )
    cap = (
        expert_capacity(t_local, e_global, num_selected, capacity_factor)
        if capacity is None
        else capacity
    )
    if cap < 1:
        raise ValueError(f"capacity must be >= 1, got {cap}")

    gate_logits = (x @ router_kernel.astype(x.dtype)).astype(jnp.float32)
    dispatch, combine, aux = make_dispatch(gate_logits, num_selected, cap)

    # bucket local tokens per global expert: [E_global, C, d]
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
    # expert e lives on device e // E_local: one all_to_all ships every
    # bucket to its owner, concatenating source devices along the slot dim
    expert_in = lax.all_to_all(expert_in, axis, split_axis=0, concat_axis=1, tiled=True)
    out = _swiglu_experts(expert_in, w_gate, w_up, w_down)  # [E_local, ep*C, d]
    # reverse route: slot-dim chunks back to their source devices
    out = lax.all_to_all(out, axis, split_axis=1, concat_axis=0, tiled=True)
    y = jnp.einsum("ecd,tec->td", out, combine.astype(x.dtype))
    return y.astype(x.dtype), lax.pmean(aux, axis)


def expert_parallel_moe(
    x: jnp.ndarray,
    router_kernel: jnp.ndarray,
    w_gate: jnp.ndarray,
    w_up: jnp.ndarray,
    w_down: jnp.ndarray,
    mesh,
    *,
    axis: str = "expert",
    num_selected: int = 2,
    capacity_factor: float = 2.0,
    capacity: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel MoE over globally-shaped tensors.

    ``x``: [T, d] with T sharded over ``mesh[axis]``; expert weights
    [E, ...] sharded the same way on their expert dim. Returns (out [T, d]
    sharded like x, aux_loss scalar).
    """
    import functools

    from unionml_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    body = functools.partial(
        expert_parallel_moe_sharded,
        axis=axis,
        num_selected=num_selected,
        capacity_factor=capacity_factor,
        capacity=capacity,
    )
    tok = P(axis, None)
    ew = P(axis, None, None)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(tok, P(None, None), ew, ew, ew),
        out_specs=(tok, P()),
        check_vma=False,
    )(x, router_kernel, w_gate, w_up, w_down)


class MoEMlp(nn.Module):
    """Expert-parallel SwiGLU MLP block.

    Weight shapes carry a leading expert dim — shard it with a
    ``PartitionRule(r"moe/.*", ("expert", ...))`` to get expert parallelism
    on the mesh (GSPMD inserts the dispatch collectives; every routed
    token is processed — no capacity drops). For explicit capacity-bucketed
    all_to_all dispatch use the functional
    :func:`expert_parallel_moe` / :func:`expert_parallel_moe_sharded` ops:
    their expert-sharded weight shapes cannot be created by module init
    outside ``shard_map``, so they are not a module knob.
    """

    num_experts: int
    num_selected: int
    hidden_dim: int
    model_dim: int
    dtype: jnp.dtype = jnp.bfloat16
    quantized: bool = False  # int8 weight-only experts (serving path)

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """x: [batch, seq, model_dim] -> (out, aux_loss)."""
        b, s, d = x.shape
        tokens = x.reshape(b * s, d)

        # router params stay float32 (compute casts down): routing updates
        # are tiny and round to zero in bf16 master weights
        router_kernel = self.param(
            "router_kernel", nn.initializers.lecun_normal(),
            (d, self.num_experts), jnp.float32,
        )
        if self.quantized:
            # int8 weights + per-(expert, out-channel) fp32 scales, filled
            # by quantize_params (LLAMA_QUANT_PATTERNS matches `moe$`)
            def qparam(name, k, n):
                q = self.param(f"{name}_q", nn.initializers.zeros,
                               (self.num_experts, k, n), jnp.int8)
                s = self.param(f"{name}_scale", nn.initializers.ones,
                               (self.num_experts, n), jnp.float32)
                return q, s

            gate_q, gate_s = qparam("w_gate", d, self.hidden_dim)
            up_q, up_s = qparam("w_up", d, self.hidden_dim)
            down_q, down_s = qparam("w_down", self.hidden_dim, d)
        else:
            w_gate = self.param(
                "w_gate", nn.initializers.lecun_normal(),
                (self.num_experts, d, self.hidden_dim), self.dtype,
            )
            w_up = self.param(
                "w_up", nn.initializers.lecun_normal(),
                (self.num_experts, d, self.hidden_dim), self.dtype,
            )
            w_down = self.param(
                "w_down", nn.initializers.lecun_normal(),
                (self.num_experts, self.hidden_dim, d), self.dtype,
            )

        gate_logits = tokens @ router_kernel.astype(tokens.dtype)
        weights, indices, aux_loss, (routing_frac, gate_frac) = top_k_routing(
            gate_logits, self.num_selected, return_stats=True
        )

        # the load-balance loss is a product of token-MEAN stats, so it is
        # not additive across sequence shards — sow the raw fractions into
        # a separate collection so sharded consumers (sequence_parallel)
        # can pmean them globally before re-forming E*sum(rf*gf). A no-op
        # (flax drops the sow) unless "moe_stats" is made mutable.
        self.sow("moe_stats", "fractions", jnp.stack([routing_frac, gate_frac]))

        # dense one-hot dispatch: static shapes, collectives inserted by
        # GSPMD when the expert dim is sharded
        dispatch = jax.nn.one_hot(indices, self.num_experts, dtype=self.dtype)
        # [T, k, E] x [T, d] -> per-expert token batches [E, T, d] weighted later
        combine = jnp.einsum("tke,tk->te", dispatch, weights.astype(self.dtype))

        mask = (combine > 0).astype(self.dtype)
        expert_in = jnp.einsum("te,td->etd", mask, tokens.astype(self.dtype))
        if self.quantized:
            # int8->compute-dtype converts fuse into the einsums (HBM reads
            # stay int8); accumulate fp32 and apply the fp32 scale BEFORE
            # the single cast down — same recipe as QuantizedDenseGeneral
            def qmm(x, w_q, w_s):
                y = jnp.einsum(
                    "etd,edh->eth", x, w_q.astype(self.dtype),
                    preferred_element_type=jnp.float32,
                )
                return (y * w_s[:, None, :]).astype(self.dtype)

            gated = jax.nn.silu(qmm(expert_in, gate_q, gate_s))
            up = qmm(expert_in, up_q, up_s)
            expert_out = qmm(gated * up, down_q, down_s)
        else:
            expert_out = _swiglu_experts(expert_in, w_gate, w_up, w_down)
        out = jnp.einsum("etd,te->td", expert_out, combine)
        return out.reshape(b, s, d).astype(self.dtype), aux_loss


def migrate_moe_router_params(params):
    """Rename old-layout MoE router params to the current layout.

    ``MoEMlp``'s router used to be an ``nn.Dense`` submodule, stored as
    ``{'router': {'kernel': ...}}``; it is now a direct fp32
    ``router_kernel`` param (routing updates are tiny and round to zero in
    bf16, so the master copy must stay fp32). Checkpoints saved under the
    old layout fail to restore with a param-tree mismatch — pass their
    params through this helper first. Works on whole-model trees: every
    nested ``{'router': {'kernel': ...}}`` is rewritten in a copied tree;
    an old ``router/bias`` is dropped (the current router is bias-free).
    Accepts any Mapping (plain dicts, ``flax.core.FrozenDict``, …) and
    returns plain nested dicts.
    """
    from collections.abc import Mapping

    if not isinstance(params, Mapping):
        return params
    out = {}
    for k, v in params.items():
        if (
            k == "router"
            and isinstance(v, Mapping)
            and set(v) <= {"kernel", "bias"}
            and "kernel" in v
        ):
            out["router_kernel"] = jnp.asarray(v["kernel"], jnp.float32)
        else:
            out[k] = migrate_moe_router_params(v)
    return out
