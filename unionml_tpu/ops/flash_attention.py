"""Pallas TPU flash-attention kernels (forward + FlashAttention-2 backward).

VMEM-tiled attention with online softmax: the forward grid walks
``(batch*heads, q_blocks, kv_blocks)`` with the KV dimension innermost —
TPU grids execute sequentially, so fp32 accumulators in VMEM scratch carry
across KV iterations (running max / normalizer / weighted sum), and the
normalized output plus the per-row logsumexp are written once on the last
KV block. Causal q/kv block pairs that are fully masked are predicated out
with ``pl.when`` (no MXU work issued).

The backward is the FlashAttention-2 recomputation scheme as two Pallas
kernels (no stored score matrix):

- ``dq`` kernel, grid ``(bh, q_blocks, kv_blocks)`` (KV innermost):
  recomputes ``p = exp(s - lse)`` per tile and accumulates
  ``dq += ds @ k`` in VMEM scratch.
- ``dkv`` kernel, grid ``(bh, kv_blocks, q_blocks)`` (Q innermost):
  accumulates ``dv += pᵀ @ dO`` and ``dk += dsᵀ @ q``.

``delta = rowsum(dO * O)`` is computed outside the kernels (XLA fuses it).
Matmul operands stay in the input dtype (bf16 on TPU) with fp32
accumulation via ``preferred_element_type`` so the MXU runs at full rate;
softmax statistics are fp32 throughout. Block shapes default to 128×128
(MXU-shaped); ragged tails are handled by masking.

On non-TPU backends (CPU tests) the kernels run in interpreter mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _tile_masks(q_start, kv_start, block_q, block_kv, q_len, kv_len, causal,
                kv_start_valid=None):
    """Validity (+ causal) mask for one [BQ, BKV] score tile.

    Causal alignment is bottom-right (the KV-cache decode convention,
    matching ``mha_reference``): with q_len < kv_len the queries are the
    LAST q_len positions, so query i sits at global position
    ``i + (kv_len - q_len)``.

    ``kv_start_valid``: optional traced scalar — kv positions BELOW it are
    masked out (left-padded prompt slots in generation prefill).
    """
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
    kv_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
    mask = jnp.logical_and(q_pos < q_len, kv_pos < kv_len)
    if causal:
        mask = jnp.logical_and(mask, q_pos + (kv_len - q_len) >= kv_pos)
    if kv_start_valid is not None:
        mask = jnp.logical_and(mask, kv_pos >= kv_start_valid)
    return mask


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, block_q, block_kv,
                num_kv_blocks, q_len, kv_len, padded=False, pad_div=1):
    if padded:
        # the padded path is forward-only (generation prefill): no
        # backward ever reads the lse, so it is neither declared nor
        # written (pure HBM savings in the memory-bound long-prefill
        # regime)
        pad_ref, o_ref, acc_ref, m_ref, l_ref = rest
        lse_ref = None
    else:
        pad_ref = None
        o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    kv_start = ki * block_kv
    # causal: skip kv blocks entirely in the future of this q block
    # bottom-right causal: query block's last GLOBAL position is
    # q_start + block_q - 1 + (kv_len - q_len)
    run = jnp.logical_or(
        jnp.logical_not(causal),
        kv_start <= q_start + block_q - 1 + (kv_len - q_len),
    )
    # pad lives in SMEM as a whole per-BATCH vector (a (1,1) VMEM block
    # would break Mosaic's (8,128) minimum-tile rule); the grid row is
    # batch*heads, so divide the head factor back out
    pad = pad_ref[pl.program_id(0) // pad_div] if padded else None
    if padded:
        # skip kv blocks that lie entirely inside this row's left padding
        run = jnp.logical_and(run, kv_start + block_kv - 1 >= pad)

    @pl.when(run)
    def _compute():
        q = q_ref[0]                               # [BQ, D] input dtype
        k = k_ref[0]                               # [BKV, D]
        # zero padded kv rows: OOB block reads are undefined (NaN in
        # interpret mode) and 0 * NaN would contaminate the p @ v matmul
        kv_valid = (kv_start + jax.lax.broadcasted_iota(jnp.int32, (block_kv, 1), 0)) < kv_len
        v = jnp.where(kv_valid, v_ref[0], jnp.zeros_like(v_ref[0]))
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                  # [BQ, BKV] fp32

        mask = _tile_masks(q_start, kv_start, block_q, block_kv, q_len, kv_len,
                           causal, kv_start_valid=pad)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]                          # [BQ, 1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(s - m_safe)
        p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(jnp.where(m_prev == NEG_INF, NEG_INF, m_prev - m_safe))
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(ki == num_kv_blocks - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)
        if lse_ref is not None:
            # padded rows (l == 0) get lse = 0 so the backward's
            # exp(NEG_INF - lse) stays 0 instead of overflowing
            lse_ref[0] = jnp.where(
                l_ref[:] > 0.0,
                m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30)),
                0.0,
            )


def _flash_fwd_bhsd(q, k, v, *, causal, scale, block_q, block_kv, interpret):
    """q,k,v: [BH, S, D] (kv heads already repeated) → (out, lse[BH,S,1])."""
    from jax.experimental.pallas import tpu as pltpu

    bh, q_len, head_dim = q.shape
    kv_len = k.shape[1]
    block_q = min(block_q, q_len)
    block_kv = min(block_kv, kv_len)
    num_q_blocks = pl.cdiv(q_len, block_q)
    num_kv_blocks = pl.cdiv(kv_len, block_kv)

    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_kv=block_kv,
        num_kv_blocks=num_kv_blocks,
        q_len=q_len,
        kv_len=kv_len,
    )
    grid = (bh, num_q_blocks, num_kv_blocks)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_kv, head_dim), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_kv, head_dim), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, q_len, head_dim), q.dtype),
            jax.ShapeDtypeStruct((bh, q_len, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _flash_fwd_padded(q, k, v, pad_b, *, causal, scale, block_q, block_kv,
                      interpret):
    """Forward-only padded flash over UNREPEATED GQA heads.

    q: [B, S, H, D]; k/v: [B, S, KVH, D] — the kv operands stay at
    kv-head width (the grid's kv index maps fold the q-head group back
    to its kv head), so no [B, S, H, D] repeated copies are ever
    materialized — this path exists for long-prefill memory, where a
    num_heads/num_kv_heads repeat would multiply fresh-k/v HBM by 4 at
    Llama geometry. ``pad_b``: [B] int32 per-BATCH first-visible kv
    position (SMEM; the kernel divides the head factor out of the grid
    row).
    """
    from jax.experimental.pallas import tpu as pltpu

    b, q_len, h, head_dim = q.shape
    kvh = k.shape[2]
    group = h // kvh
    kv_len = k.shape[1]
    qb = _to_bhsd(q)                      # [B*H, S, D]
    kb = _to_bhsd(k)                      # [B*KVH, S, D]
    vb = _to_bhsd(v)
    block_q = min(block_q, q_len)
    block_kv = min(block_kv, kv_len)
    num_q_blocks = pl.cdiv(q_len, block_q)
    num_kv_blocks = pl.cdiv(kv_len, block_kv)

    def kv_row(bh):
        return (bh // h) * kvh + (bh % h) // group

    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_kv=block_kv,
        num_kv_blocks=num_kv_blocks,
        q_len=q_len,
        kv_len=kv_len,
        padded=True,
        pad_div=h,
    )
    grid = (b * h, num_q_blocks, num_kv_blocks)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec(
                (1, block_kv, head_dim), lambda bh, qi, ki: (kv_row(bh), ki, 0)
            ),
            pl.BlockSpec(
                (1, block_kv, head_dim), lambda bh, qi, ki: (kv_row(bh), ki, 0)
            ),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, head_dim), lambda bh, qi, ki: (bh, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, q_len, head_dim), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, vb, jnp.asarray(pad_b, jnp.int32))
    return _from_bhsd(out, b, h)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc, *,
               scale, causal, block_q, block_kv, num_kv_blocks, q_len, kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = qi * block_q
    kv_start = ki * block_kv
    # bottom-right causal: query block's last GLOBAL position is
    # q_start + block_q - 1 + (kv_len - q_len)
    run = jnp.logical_or(
        jnp.logical_not(causal),
        kv_start <= q_start + block_q - 1 + (kv_len - q_len),
    )

    @pl.when(run)
    def _compute():
        # zero padded rows: ragged-tail OOB block reads are undefined (NaN
        # in interpret mode) and would poison the accumulators via 0 * NaN
        q_valid = (q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)) < q_len
        kv_valid = (kv_start + jax.lax.broadcasted_iota(jnp.int32, (block_kv, 1), 0)) < kv_len
        q = jnp.where(q_valid, q_ref[0], jnp.zeros_like(q_ref[0]))
        k = jnp.where(kv_valid, k_ref[0], jnp.zeros_like(k_ref[0]))
        v = jnp.where(kv_valid, v_ref[0], jnp.zeros_like(v_ref[0]))
        do = jnp.where(q_valid, do_ref[0], jnp.zeros_like(do_ref[0]))
        lse = jnp.where(q_valid, lse_ref[0], 0.0)   # [BQ, 1] fp32
        delta = jnp.where(q_valid, delta_ref[0], 0.0)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        mask = _tile_masks(q_start, kv_start, block_q, block_kv, q_len, kv_len, causal)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)  # [BQ, BKV] fp32
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                           # [BQ, BKV]
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == num_kv_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                dk_acc, dv_acc, *,
                scale, causal, block_q, block_kv, num_q_blocks, q_len, kv_len):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    kv_start = ki * block_kv
    run = jnp.logical_or(
        jnp.logical_not(causal),
        q_start + block_q - 1 + (kv_len - q_len) >= kv_start,
    )

    @pl.when(run)
    def _compute():
        # zero padded rows (see _dq_kernel)
        q_valid = (q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)) < q_len
        kv_valid = (kv_start + jax.lax.broadcasted_iota(jnp.int32, (block_kv, 1), 0)) < kv_len
        q = jnp.where(q_valid, q_ref[0], jnp.zeros_like(q_ref[0]))
        k = jnp.where(kv_valid, k_ref[0], jnp.zeros_like(k_ref[0]))
        v = jnp.where(kv_valid, v_ref[0], jnp.zeros_like(v_ref[0]))
        do = jnp.where(q_valid, do_ref[0], jnp.zeros_like(do_ref[0]))
        lse = jnp.where(q_valid, lse_ref[0], 0.0)   # [BQ, 1]
        delta = jnp.where(q_valid, delta_ref[0], 0.0)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        mask = _tile_masks(q_start, kv_start, block_q, block_kv, q_len, kv_len, causal)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)  # [BQ, BKV]
        p_cast = p.astype(do.dtype)
        dv_acc[:] += jax.lax.dot_general(
            p_cast, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )                                           # [BKV, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                           # [BQ, BKV]
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )                                           # [BKV, D]

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_bhsd(q, k, v, do, lse, delta, *, causal, scale, block_q, block_kv,
                    interpret):
    """[BH, S, D] gradients via the two FlashAttention-2 backward kernels."""
    from jax.experimental.pallas import tpu as pltpu

    bh, q_len, head_dim = q.shape
    kv_len = k.shape[1]
    block_q = min(block_q, q_len)
    block_kv = min(block_kv, kv_len)
    num_q_blocks = pl.cdiv(q_len, block_q)
    num_kv_blocks = pl.cdiv(kv_len, block_kv)

    q_spec = pl.BlockSpec((1, block_q, head_dim), lambda b, i, j: (b, i, 0))
    kv_spec_dq = pl.BlockSpec((1, block_kv, head_dim), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, block_q=block_q,
            block_kv=block_kv, num_kv_blocks=num_kv_blocks,
            q_len=q_len, kv_len=kv_len,
        ),
        grid=(bh, num_q_blocks, num_kv_blocks),
        in_specs=[q_spec, kv_spec_dq, kv_spec_dq, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, q_len, head_dim), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dkv grid: kv blocks outer, q blocks inner
    q_spec_kv = pl.BlockSpec((1, block_q, head_dim), lambda b, i, j: (b, j, 0))
    kv_spec_kv = pl.BlockSpec((1, block_kv, head_dim), lambda b, i, j: (b, i, 0))
    row_spec_kv = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, block_q=block_q,
            block_kv=block_kv, num_q_blocks=num_q_blocks,
            q_len=q_len, kv_len=kv_len,
        ),
        grid=(bh, num_kv_blocks, num_q_blocks),
        in_specs=[q_spec_kv, kv_spec_kv, kv_spec_kv, q_spec_kv, row_spec_kv, row_spec_kv],
        out_specs=[kv_spec_kv, kv_spec_kv],
        out_shape=[
            jax.ShapeDtypeStruct((bh, kv_len, head_dim), k.dtype),
            jax.ShapeDtypeStruct((bh, kv_len, head_dim), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, head_dim), jnp.float32),
            pltpu.VMEM((block_kv, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _to_bhsd(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _from_bhsd(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_kv):
    out, _ = _flash_fwd_res(q, k, v, causal, scale, block_q, block_kv)
    return out


def _flash_fwd_res(q, k, v, causal, scale, block_q, block_kv):
    from unionml_tpu.ops.attention import _repeat_kv

    num_q_heads = q.shape[2]
    k_r = _repeat_kv(k, num_q_heads)
    v_r = _repeat_kv(v, num_q_heads)
    out_bhsd, lse = _flash_fwd_bhsd(
        _to_bhsd(q), _to_bhsd(k_r), _to_bhsd(v_r),
        causal=causal, scale=scale, block_q=block_q, block_kv=block_kv,
        interpret=_interpret(),
    )
    b, _, h, _ = q.shape
    return _from_bhsd(out_bhsd, b, h), (q, k, v, out_bhsd, lse)


def _flash_bwd(causal, scale, block_q, block_kv, residuals, g):
    from unionml_tpu.ops.attention import _repeat_kv

    q, k, v, out_bhsd, lse = residuals
    b, s, h, d = q.shape
    kv_heads = k.shape[2]
    k_r = _repeat_kv(k, h)
    v_r = _repeat_kv(v, h)
    do = _to_bhsd(g)
    delta = jnp.sum(
        do.astype(jnp.float32) * out_bhsd.astype(jnp.float32), axis=-1, keepdims=True
    )
    dq, dk_r, dv_r = _flash_bwd_bhsd(
        _to_bhsd(q), _to_bhsd(k_r), _to_bhsd(v_r), do, lse, delta,
        causal=causal, scale=scale, block_q=block_q, block_kv=block_kv,
        interpret=_interpret(),
    )
    dq = _from_bhsd(dq, b, h)
    dk = _from_bhsd(dk_r, b, h)
    dv = _from_bhsd(dv_r, b, h)
    if kv_heads != h:
        # GQA: sum gradients over the repeated query-head groups
        group = h // kv_heads
        kv_len = k.shape[1]
        dk = dk.reshape(b, kv_len, kv_heads, group, d).sum(axis=3)
        dv = dv.reshape(b, kv_len, kv_heads, group, d).sum(axis=3)
    return dq, dk, dv


_flash.defvjp(_flash_fwd_res, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_kv: Optional[int] = None,
    kv_valid_start: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Flash attention over [B,S,H,D] tensors (GQA-aware, differentiable).

    Block defaults are path-dependent (``block_kv=None`` picks them):
    the differentiable path uses 512×512 — TPU grids pay a fixed
    per-program cost, so fewer/bigger blocks win as long as the working
    set fits VMEM (measured on v5e: 512-blocks are ~2x faster than
    128-blocks at S=4096 and ~7x faster than XLA attention forward at
    that length); the forward-only padded path (``kv_valid_start``)
    widens kv blocks to ``min(2048·128/head_dim, kv_len)`` — measured
    6.5% end-to-end at 4k prompts. Blocks are clamped to the sequence
    length, so short sequences degenerate to a single tile per
    (batch, head) — the best flash configuration there too.

    ``kv_valid_start``: optional [B] int32 — per-row first visible kv
    position; kv positions below it are masked out (left-padded prompts
    in generation prefill). FORWARD-ONLY: this path has no backward
    (generation never differentiates); differentiating it raises.
    Fully-masked query rows (q inside the padding) return zeros.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if kv_valid_start is None:
        # training/differentiable path: 512x512 is the measured optimum
        # (docstring above)
        return _flash(q, k, v, causal, scale, block_q, block_kv or 512)
    if block_kv is None:
        # forward-only padded path (generation prefill): wider kv blocks
        # amortize the per-program grid cost — measured end-to-end 6.5%
        # at 1.5B x 4k prompts (block_kv 512 -> 2048, 883 -> 829 ms).
        # 2048 was the largest that compiled at head_dim 128; scale the
        # cap down for larger head dims so the kv VMEM tile footprint
        # (block_kv x head_dim) stays at the measured-safe budget
        cap = min(2048, max(512, 2048 * 128 // q.shape[-1]))
        block_kv = min(cap, k.shape[1])
    return _flash_fwd_padded(
        q, k, v, kv_valid_start,
        causal=causal, scale=scale, block_q=block_q, block_kv=block_kv,
        interpret=_interpret(),
    )
