"""Pallas TPU flash-attention kernel.

VMEM-tiled attention with online softmax: the grid walks
``(batch*heads, q_blocks, kv_blocks)`` with the KV dimension innermost —
TPU grids execute sequentially, so fp32 accumulators in VMEM scratch carry
across KV iterations (running max / normalizer / weighted sum), and the
normalized output is written once on the last KV block. Causal q/kv block
pairs that are fully masked are predicated out with ``pl.when`` (no MXU
work issued).

Block shapes default to 128×128 (MXU-shaped); scores accumulate in fp32
(``preferred_element_type``) regardless of input dtype, so bf16 inputs are
safe. Backward is a recompute VJP against the blockwise reference — exact
gradients, no stored score matrix.

On non-TPU backends (CPU tests) the kernel runs in interpreter mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, scale, causal,
            block_q, block_kv, num_kv_blocks, q_len, kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    kv_start = ki * block_kv
    # causal: skip kv blocks entirely in the future of this q block
    run = jnp.logical_or(
        jnp.logical_not(causal), kv_start <= q_start + block_q - 1
    )

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [BQ, D]
        k = k_ref[0].astype(jnp.float32)          # [BKV, D]
        v = v_ref[0].astype(jnp.float32)          # [BKV, D]
        # zero padded kv rows: OOB block reads are undefined (NaN in
        # interpret mode) and 0 * NaN would contaminate the p @ v matmul
        kv_valid = (kv_start + jax.lax.broadcasted_iota(jnp.int32, (block_kv, 1), 0)) < kv_len
        v = jnp.where(kv_valid, v, 0.0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                  # [BQ, BKV]

        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
        kv_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
        # mask padded q rows (ragged last block) and padded kv columns
        mask = jnp.logical_and(q_pos < q_len, kv_pos < kv_len)
        if causal:
            mask = jnp.logical_and(mask, q_pos >= kv_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]                          # [BQ, 1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(s - m_safe)
        p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(jnp.where(m_prev == NEG_INF, NEG_INF, m_prev - m_safe))
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = m_new

    @pl.when(ki == num_kv_blocks - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _flash_fwd_bhsd(q, k, v, *, causal, scale, block_q, block_kv, interpret):
    """q,k,v: [BH, S, D] (kv heads already repeated)."""
    from jax.experimental.pallas import tpu as pltpu

    bh, q_len, head_dim = q.shape
    kv_len = k.shape[1]
    block_q = min(block_q, q_len)
    block_kv = min(block_kv, kv_len)
    num_q_blocks = pl.cdiv(q_len, block_q)
    num_kv_blocks = pl.cdiv(kv_len, block_kv)

    kernel = functools.partial(
        _kernel,
        scale=scale,
        causal=causal,
        block_q=block_q,
        block_kv=block_kv,
        num_kv_blocks=num_kv_blocks,
        q_len=q_len,
        kv_len=kv_len,
    )
    grid = (bh, num_q_blocks, num_kv_blocks)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_kv, head_dim), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_kv, head_dim), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, q_len, head_dim), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_kv):
    interpret = jax.devices()[0].platform != "tpu"
    num_q_heads = q.shape[2]
    from unionml_tpu.ops.attention import _repeat_kv

    k_r = _repeat_kv(k, num_q_heads)
    v_r = _repeat_kv(v, num_q_heads)

    def to_bhsd(x):
        b, s, h, d = x.shape
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    out = _flash_fwd_bhsd(
        to_bhsd(q), to_bhsd(k_r), to_bhsd(v_r),
        causal=causal, scale=scale, block_q=block_q, block_kv=block_kv,
        interpret=interpret,
    )
    b, s, h, d = q.shape
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, causal, scale, block_q, block_kv):
    return _flash(q, k, v, causal, scale, block_q, block_kv), (q, k, v)


def _flash_bwd(causal, scale, block_q, block_kv, residuals, g):
    # recompute VJP against the blockwise reference: exact gradients with
    # O(S·block) memory, no stored score matrix
    from unionml_tpu.ops.attention import blockwise_attention

    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, causal=causal, scale=scale, block_size=block_kv
        ),
        q, k, v,
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_kv: int = 128,
) -> jnp.ndarray:
    """Flash attention over [B,S,H,D] tensors (GQA-aware, differentiable)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash(q, k, v, causal, scale, block_q, block_kv)
