"""Packed-int4 weight-only matmul (Pallas) — the decode bandwidth lever.

8B int8 serving sits at the HBM bound: every decoded token streams the
full weight set (BASELINE.md rounds 2-4; p50 468 ms is within ~3% of
the int8-traffic bound). int4 weights halve the bytes again — but this
backend cannot move native ``jnp.int4`` across the jit boundary (plugin
arg-signature recursion) and XLA materializes any unpack it is shown
(measured 0.65-1.02x — worse or nil). So the int4 path stores TWO
NIBBLES PER int8 BYTE and a Pallas kernel unpacks in VMEM, feeding the
MXU directly — HBM reads stay at the packed width. Measured on the
decode-faithful stream probe (32 layers of resident MLP weights per
step, one v5e): int8 20.1 ms/step → int4 **13.0 ms/step (1.54x)**.

Packing layout (``pack_int4``): output channels are tiled by ``TILE_N``;
within tile ``j`` the LOW nibbles hold channels ``[j*T, j*T + T/2)`` and
the HIGH nibbles ``[j*T + T/2, (j+1)*T)``, so the kernel's two
per-nibble matmuls write contiguous slabs and the output needs no
permutation. Mosaic constraints honored: nibble math runs in int32
(int8 shifts don't legalize), scales apply OUTSIDE the kernel (1D fp32
operands hit XLA/Mosaic layout mismatches), and the Pallas path engages
only for row counts ≤ ``MAX_PALLAS_ROWS`` and tile-divisible N — other
shapes (prefill's flattened rows, tiny test geometries) fall back to an
XLA unpack with identical semantics (prefill is compute-amortized; the
bandwidth lever only matters for decode).

Quantization (``quantize_kernel_int4``): symmetric per-output-channel
absmax/7 — coarser than int8's /127; serving quality at 4-bit normally
wants group-wise scales, which compose with this kernel (scales are
outside) but are not implemented here. The shipped recipe is the
latency configuration; quality evaluation needs real weights.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "MAX_PALLAS_ROWS",
    "int4_matmul",
    "pack_int4",
    "quantize_kernel_int4",
    "unpack_int4",
]

TILE_N = 512          # output-channel tile; N must divide by a tile choice
MAX_PALLAS_ROWS = 64  # decode/verify row counts; larger rows → XLA path


# per-program VMEM budget for the weight-side buffers: packed int8 +
# int32 nibble temps + bf16 operands ≈ 9 bytes per packed element; the
# v5e scoped-vmem limit is 16 MB per kernel (leave headroom for x/out)
_VMEM_WEIGHT_BYTES = 11_000_000


def _grid_for(n: int, k: int):
    """Pick ``(tile_n, k_block)`` for N output channels at contraction
    width K. Mosaic needs the packed block's last dim (tile/2) to
    divide 128 or equal the full packed width, so multi-tile means
    tile ∈ {512, 256}; any even N works single-tile. Big K blows the
    scoped-VMEM budget (the int32 unpack temps scale with K x TILE), so
    K splits into grid blocks with output accumulation — k_block halves
    until the weight-side buffers fit (K=14336 down-projections run
    tile 512 x k_block 3584). Returns ``(0, 0)`` when N is odd (cannot
    pack two nibbles per byte)."""
    if n % 2:
        return 0, 0
    candidates = [t for t in (512, 256) if n % t == 0] or [n]
    for t in candidates:
        kb = k
        while 9 * kb * (t // 2) > _VMEM_WEIGHT_BYTES and kb % 2 == 0:
            kb //= 2
        if 9 * kb * (t // 2) <= _VMEM_WEIGHT_BYTES and (
            kb == k or kb % 128 == 0
        ):
            return t, kb
    return 0, 0


def pack_int4(nibbles: jnp.ndarray, tile_n: int) -> jnp.ndarray:
    """Pack int8 nibble values (in [-8, 7]) ``[K, N]`` → ``[K, N/2]``
    int8, tile-slab order (see module docstring)."""
    k, n = nibbles.shape
    t = nibbles.reshape(k, n // tile_n, tile_n)
    lo = t[:, :, : tile_n // 2]
    hi = t[:, :, tile_n // 2 :]
    p = (lo.astype(jnp.uint8) & 0xF) | ((hi.astype(jnp.uint8) & 0xF) << 4)
    return p.reshape(k, n // 2).astype(jnp.int8)


def unpack_int4(packed: jnp.ndarray, tile_n: int) -> jnp.ndarray:
    """Inverse of :func:`pack_int4`: ``[K, N/2]`` int8 → ``[K, N]`` int8
    nibble values (the XLA-fallback dequant and the test oracle)."""
    k, half = packed.shape
    q = packed.astype(jnp.int32)
    hi = q >> 4
    lo = ((q & 15) ^ 8) - 8
    t = jnp.concatenate(
        [
            lo.reshape(k, half // (tile_n // 2), tile_n // 2),
            hi.reshape(k, half // (tile_n // 2), tile_n // 2),
        ],
        axis=2,
    )
    return t.reshape(k, 2 * half).astype(jnp.int8)


def _kernel(x_ref, wp_ref, o_ref):
    from jax.experimental import pallas as pl

    q = wp_ref[...].astype(jnp.int32)  # int8 shifts don't legalize in Mosaic
    hi = q >> 4                        # arithmetic shift == floor(q/16)
    lo = ((q & 15) ^ 8) - 8            # sign-extend the low nibble
    xb = x_ref[...]
    # weights convert to the CALLER'S compute dtype (the lm_head keeps
    # its fp32-logits contract; everything else runs bf16 on the MXU)
    y_lo = jax.lax.dot_general(
        xb, lo.astype(xb.dtype),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    y_hi = jax.lax.dot_general(
        xb, hi.astype(xb.dtype),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    partial_out = jnp.concatenate([y_lo, y_hi], axis=1)

    # K is blocked over the innermost grid dim with output accumulation
    @pl.when(pl.program_id(1) == 0)
    def _zero():
        o_ref[...] = jnp.zeros_like(o_ref)

    o_ref[...] += partial_out


@functools.partial(
    jax.jit, static_argnames=("n", "tile_n", "k_block", "interpret")
)
def _pallas_int4(x, packed, *, n: int, tile_n: int, k_block: int, interpret: bool):
    from jax.experimental import pallas as pl

    rows, k = x.shape
    grid = (n // tile_n, k // k_block)  # k innermost: accumulation order
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, k_block), lambda j, kb: (0, kb)),
            pl.BlockSpec((k_block, tile_n // 2), lambda j, kb: (kb, j)),
        ],
        out_specs=pl.BlockSpec((rows, tile_n), lambda j, kb: (0, j)),
        out_shape=jax.ShapeDtypeStruct((rows, n), jnp.float32),
        interpret=interpret,
    )(x, packed)


def int4_matmul(
    x: jnp.ndarray,
    packed: jnp.ndarray,
    scale: jnp.ndarray,
    *,
    tile_n: int,
    dtype=jnp.bfloat16,
) -> jnp.ndarray:
    """``x [rows, K] @ W4`` where ``W4`` is ``pack_int4``-packed
    ``[K, N/2]`` with per-output-channel fp32 ``scale [N]``.

    Decode-sized row counts on TPU run the Pallas kernel (HBM reads at
    the packed width); anything else takes the XLA unpack path — same
    math, standard traffic. The compute dtype follows ``dtype`` when it
    is a float type (fp32 for the LM head's logits contract, bf16
    otherwise), matching ``QuantizedDenseGeneral``'s behavior.
    """
    rows = x.shape[0]
    n = scale.shape[0]
    compute = dtype if jnp.issubdtype(dtype, jnp.floating) else jnp.bfloat16
    _, k_block = _grid_for(n, x.shape[1])
    use_pallas = 0 < rows <= MAX_PALLAS_ROWS and tile_n > 0 and k_block > 0
    if use_pallas:
        interpret = jax.default_backend() != "tpu"
        y = _pallas_int4(
            x.astype(compute), packed, n=n, tile_n=tile_n,
            k_block=k_block, interpret=interpret,
        )
    else:
        w = unpack_int4(packed, tile_n).astype(compute)
        y = jax.lax.dot_general(
            x.astype(compute), w,
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )
    return (y * scale).astype(dtype)


def quantize_kernel_int4(w2d: jnp.ndarray, tile_n: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-output-channel int4: ``[K, N]`` fp → ``(packed
    [K, N/2] int8, scale [N] fp32)``. ``tile_n`` must match the serving
    call's tile (it bakes the slab order into the packing)."""
    w = jnp.asarray(w2d, jnp.float32)
    absmax = jnp.max(jnp.abs(w), axis=0)                 # [N]
    scale = jnp.where(absmax > 0, absmax / 7.0, 1.0)
    nib = jnp.clip(jnp.round(w / scale), -8, 7).astype(jnp.int8)
    return pack_int4(nib, tile_n), scale.astype(jnp.float32)


def tile_for(n: int, k: int) -> int:
    """The tile the serving layer should bake for ``N`` output channels
    at contraction width ``K`` (0 = no conforming tile; the layer must
    stay int8)."""
    return _grid_for(n, k)[0]
