"""Packed-int4 weight-only matmul (Pallas) — the decode bandwidth lever.

8B int8 serving sits at the HBM bound: every decoded token streams the
full weight set (BASELINE.md rounds 2-4; p50 468 ms is within ~3% of
the int8-traffic bound). int4 weights halve the bytes again — but this
backend cannot move native ``jnp.int4`` across the jit boundary (plugin
arg-signature recursion) and XLA materializes any unpack it is shown
(measured 0.65-1.02x — worse or nil). So the int4 path stores TWO
NIBBLES PER int8 BYTE and a Pallas kernel unpacks in VMEM, feeding the
MXU directly — HBM reads stay at the packed width. Measured on the
decode-faithful stream probe (32 layers of resident MLP weights per
step, one v5e): int8 20.1 ms/step → int4 **13.0 ms/step (1.54x)**.

Packing layout (``pack_int4``): output channels are tiled by ``TILE_N``;
within tile ``j`` the LOW nibbles hold channels ``[j*T, j*T + T/2)`` and
the HIGH nibbles ``[j*T + T/2, (j+1)*T)``, so the kernel's two
per-nibble matmuls write contiguous slabs and the output needs no
permutation. Mosaic constraints honored: nibble math runs in int32
(int8 shifts don't legalize), scales apply OUTSIDE the kernel (1D fp32
operands hit XLA/Mosaic layout mismatches), and the Pallas path engages
only for row counts ≤ ``MAX_PALLAS_ROWS`` and tile-divisible N — other
shapes (prefill's flattened rows, tiny test geometries) fall back to an
XLA unpack with identical semantics (prefill is compute-amortized; the
bandwidth lever only matters for decode).

Quantization (``quantize_kernel_int4``): symmetric per-output-channel
absmax/7 — coarser than int8's /127; serving quality at 4-bit normally
wants group-wise scales, which compose with this kernel (scales are
outside) but are not implemented here. The shipped recipe is the
latency configuration; quality evaluation needs real weights.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "MAX_PALLAS_ROWS",
    "int4_matmul",
    "pack_int4",
    "quantize_kernel_int4",
    "unpack_int4",
]

TILE_N = 512          # output-channel tile; N must divide by a tile choice
MAX_PALLAS_ROWS = 64  # decode/verify row counts; larger rows → XLA path


# per-program VMEM budget for the weight-side buffers: packed int8 +
# int32 nibble temps + bf16 operands ≈ 9 bytes per packed element; the
# v5e scoped-vmem limit is 16 MB per kernel (leave headroom for x/out)
_VMEM_WEIGHT_BYTES = 11_000_000


def _grid_for(n: int, k: int, shards: int = 1, group_size: int = 0):
    """Pick ``(tile_n, k_block)`` for N output channels at contraction
    width K. Mosaic needs the packed block's last dim (tile/2) to
    divide 128 or equal the full packed width, so multi-tile means
    tile ∈ {512, 256, 128}; any even N works single-tile. Big K blows
    the scoped-VMEM budget (the int32 unpack temps scale with K x TILE),
    so K splits into grid blocks with output accumulation — k_block
    halves until the weight-side buffers fit (K=14336 down-projections
    run tile 512 x k_block 3584). Returns ``(0, 0)`` when N is odd
    (cannot pack two nibbles per byte).

    ``shards``: tensor-parallel degree the packing must survive — the
    tile must divide the PER-DEVICE channel count ``n // shards`` so
    shard boundaries land on slab boundaries (any divisor of ``shards``
    then also works at serve time). A 128-tile is a valid PACKING but
    not a Pallas-servable block (its packed width 64 breaks the Mosaic
    lane rule unless it spans the whole array) — ``int4_matmul`` routes
    such layers through the XLA unpack path. ``group_size``: group-wise
    scale granularity — k_block additionally divides the group so each
    grid step's partial product carries ONE scale row (see
    :func:`int4_matmul`'s grouped path)."""
    if n % 2 or n % max(1, shards):
        return 0, 0
    local = n // max(1, shards)
    candidates = [t for t in (512, 256, 128) if local % t == 0]
    if not candidates and shards == 1:
        candidates = [n]  # single-tile: any even width
    for t in candidates:
        kb = _k_block_for(k, t, group_size)
        if kb:
            return t, kb
    return 0, 0


def _k_block_for(k: int, tile_n: int, group_size: int = 0) -> int:
    """The K grid block for a GIVEN tile: halve from K (or the scale
    group) until the weight-side VMEM buffers fit. Sized against the
    caller's actual tile — a first-fit recompute against a different
    candidate would fragment the K grid (review finding)."""
    kb = min(k, group_size) if group_size else k
    while 9 * kb * (tile_n // 2) > _VMEM_WEIGHT_BYTES and kb % 2 == 0:
        kb //= 2
    if 9 * kb * (tile_n // 2) <= _VMEM_WEIGHT_BYTES and (
        kb == k or kb % 128 == 0
    ):
        return kb
    return 0


def pack_int4(nibbles: jnp.ndarray, tile_n: int) -> jnp.ndarray:
    """Pack int8 nibble values (in [-8, 7]) ``[K, N]`` → ``[K, N/2]``
    int8, tile-slab order (see module docstring)."""
    k, n = nibbles.shape
    t = nibbles.reshape(k, n // tile_n, tile_n)
    lo = t[:, :, : tile_n // 2]
    hi = t[:, :, tile_n // 2 :]
    p = (lo.astype(jnp.uint8) & 0xF) | ((hi.astype(jnp.uint8) & 0xF) << 4)
    return p.reshape(k, n // 2).astype(jnp.int8)


def unpack_int4(packed: jnp.ndarray, tile_n: int) -> jnp.ndarray:
    """Inverse of :func:`pack_int4`: ``[K, N/2]`` int8 → ``[K, N]`` int8
    nibble values (the XLA-fallback dequant and the test oracle)."""
    k, half = packed.shape
    q = packed.astype(jnp.int32)
    hi = q >> 4
    lo = ((q & 15) ^ 8) - 8
    t = jnp.concatenate(
        [
            lo.reshape(k, half // (tile_n // 2), tile_n // 2),
            hi.reshape(k, half // (tile_n // 2), tile_n // 2),
        ],
        axis=2,
    )
    return t.reshape(k, 2 * half).astype(jnp.int8)


def _kernel(x_ref, wp_ref, o_ref):
    from jax.experimental import pallas as pl

    q = wp_ref[...].astype(jnp.int32)  # int8 shifts don't legalize in Mosaic
    hi = q >> 4                        # arithmetic shift == floor(q/16)
    lo = ((q & 15) ^ 8) - 8            # sign-extend the low nibble
    xb = x_ref[...]
    # weights convert to the CALLER'S compute dtype (the lm_head keeps
    # its fp32-logits contract; everything else runs bf16 on the MXU)
    y_lo = jax.lax.dot_general(
        xb, lo.astype(xb.dtype),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    y_hi = jax.lax.dot_general(
        xb, hi.astype(xb.dtype),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    partial_out = jnp.concatenate([y_lo, y_hi], axis=1)

    # K is blocked over the innermost grid dim with output accumulation
    @pl.when(pl.program_id(1) == 0)
    def _zero():
        o_ref[...] = jnp.zeros_like(o_ref)

    o_ref[...] += partial_out


@functools.partial(
    jax.jit, static_argnames=("n", "tile_n", "k_block", "interpret")
)
def _pallas_int4(x, packed, *, n: int, tile_n: int, k_block: int, interpret: bool):
    from jax.experimental import pallas as pl

    rows, k = x.shape
    grid = (n // tile_n, k // k_block)  # k innermost: accumulation order
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, k_block), lambda j, kb: (0, kb)),
            pl.BlockSpec((k_block, tile_n // 2), lambda j, kb: (kb, j)),
        ],
        out_specs=pl.BlockSpec((rows, tile_n), lambda j, kb: (0, j)),
        out_shape=jax.ShapeDtypeStruct((rows, n), jnp.float32),
        interpret=interpret,
    )(x, packed)


def _kernel_grouped(x_ref, wp_ref, s_ref, o_ref, *, ratio: int):
    """Group-wise variant: ``k_block`` divides the scale group, so this
    step's whole partial product carries ONE scale row — the scale
    multiply rides the small fp32 partial, never a materialized weight
    tile. ``s_ref`` holds the tile's FULL [K/g, tile] scale slab (a
    (1, tile) block would violate Mosaic's second-minor-divisible-by-8
    rule; the slab is ~64 KB and the kernel slices its group row
    dynamically — ``ratio = group_size / k_block`` maps the K grid
    index to it)."""
    from jax.experimental import pallas as pl

    q = wp_ref[...].astype(jnp.int32)
    hi = q >> 4
    lo = ((q & 15) ^ 8) - 8
    xb = x_ref[...]
    y_lo = jax.lax.dot_general(
        xb, lo.astype(xb.dtype),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    y_hi = jax.lax.dot_general(
        xb, hi.astype(xb.dtype),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    kb = pl.program_id(1)
    row = kb if ratio == 1 else jax.lax.div(kb, jnp.int32(ratio))
    # dynamic REF load (value-level dynamic_slice has no TC lowering)
    scale_row = s_ref[pl.dslice(row, 1), :]
    partial_out = jnp.concatenate([y_lo, y_hi], axis=1) * scale_row

    @pl.when(kb == 0)
    def _zero():
        o_ref[...] = jnp.zeros_like(o_ref)

    o_ref[...] += partial_out


@functools.partial(
    jax.jit,
    static_argnames=("n", "tile_n", "k_block", "group_size", "interpret"),
)
def _pallas_int4_grouped(
    x, packed, scale_slab, *, n: int, tile_n: int, k_block: int,
    group_size: int, interpret: bool,
):
    """``scale``: fp32 [K/g, N] in NATURAL channel order — within tile
    ``j`` the kernel's ``concat([y_lo, y_hi])`` partial spans channels
    ``[j*t, (j+1)*t)`` contiguously (the pack layout's whole point), so
    the per-block [1, tile] scale slice lines up with no reorder."""
    from jax.experimental import pallas as pl

    rows, k = x.shape
    grid = (n // tile_n, k // k_block)
    # k_block | group_size: K-block kb reads scale row kb / ratio
    ratio = group_size // k_block
    return pl.pallas_call(
        functools.partial(_kernel_grouped, ratio=ratio),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, k_block), lambda j, kb: (0, kb)),
            pl.BlockSpec((k_block, tile_n // 2), lambda j, kb: (kb, j)),
            # full scale-row slab per tile (first dim equal to the
            # array's — Mosaic's block rule): the kernel slices its row
            pl.BlockSpec((k // group_size, tile_n), lambda j, kb: (0, j)),
        ],
        out_specs=pl.BlockSpec((rows, tile_n), lambda j, kb: (0, j)),
        out_shape=jax.ShapeDtypeStruct((rows, n), jnp.float32),
        interpret=interpret,
    )(x, packed, scale_slab)


def int4_matmul(
    x: jnp.ndarray,
    packed: jnp.ndarray,
    scale: jnp.ndarray,
    *,
    tile_n: int,
    dtype=jnp.bfloat16,
    group_size: int = 0,
) -> jnp.ndarray:
    """``x [rows, K] @ W4`` where ``W4`` is ``pack_int4``-packed
    ``[K, N/2]`` with fp32 ``scale``: per-output-channel ``[N]``
    (``group_size=0``) or group-wise ``[K/group_size, N]`` — the
    standard 4-bit quality recipe (each K-group of an output channel
    carries its own scale; absmax outliers then poison ``group_size``
    weights instead of the whole column).

    Decode-sized row counts on TPU run the Pallas kernel (HBM reads at
    the packed width; grouped scales ride the small fp32 partials inside
    the kernel — K-blocks divide the group, so no weight tile is ever
    materialized at fp width); anything else takes the XLA unpack path —
    same math, standard traffic. The compute dtype follows ``dtype``
    when it is a float type (fp32 for the LM head's logits contract,
    bf16 otherwise), matching ``QuantizedDenseGeneral``'s behavior.
    """
    rows, k = x.shape
    n = scale.shape[-1]
    if group_size:
        if scale.ndim != 2 or scale.shape[0] != k // group_size:
            raise ValueError(
                f"group_size={group_size} needs scale [K/g, N] = "
                f"[{k // group_size}, {n}], got {scale.shape}"
            )
    elif scale.ndim != 1:
        raise ValueError(
            f"per-channel int4 needs scale [N], got {scale.shape} — pass "
            "group_size for group-wise scales"
        )
    compute = dtype if jnp.issubdtype(dtype, jnp.floating) else jnp.bfloat16
    k_block = _k_block_for(k, tile_n, group_size) if tile_n > 0 else 0
    # Mosaic lane rule: the packed operand's block width (tile/2) must
    # be a multiple of 128 or span the whole packed array — a 128-tile
    # (TP-packed k/v geometry served on one chip) is a valid PACKING but
    # not a servable Pallas block, so it decodes via the XLA path
    mosaic_ok = tile_n % 256 == 0 or tile_n == n
    use_pallas = (
        0 < rows <= MAX_PALLAS_ROWS and tile_n > 0 and k_block > 0
        and mosaic_ok
    )
    if (
        group_size and group_size % 128 and tile_n > 0
        and 0 < rows <= MAX_PALLAS_ROWS
    ):
        # fires at trace time, once per compiled shape: the operator
        # asked for the decode-bandwidth configuration but loses it
        import warnings

        warnings.warn(
            f"int4 group_size={group_size} is not a multiple of 128: the "
            "Pallas decode kernel cannot block K below 128 (Mosaic lane "
            "rule, measured on v5e), so decode takes the XLA unpack path "
            "at full-width weight reads. Use group_size=128 to keep the "
            "packed-width bandwidth win (measured ~1.4% over "
            "per-channel).",
            stacklevel=2,
        )
    if use_pallas:
        interpret = jax.default_backend() != "tpu"
        if group_size:
            y = _pallas_int4_grouped(
                x.astype(compute), packed, scale, n=n, tile_n=tile_n,
                k_block=k_block, group_size=group_size, interpret=interpret,
            )
            return y.astype(dtype)
        y = _pallas_int4(
            x.astype(compute), packed, n=n, tile_n=tile_n,
            k_block=k_block, interpret=interpret,
        )
        return (y * scale).astype(dtype)
    w = unpack_int4(packed, tile_n)
    if group_size:
        # fallback (prefill / compute-bound shapes): dequantize at fp32
        # so group scales keep their precision, then one matmul
        w_f = w.astype(jnp.float32) * jnp.repeat(scale, group_size, axis=0)
        y = jax.lax.dot_general(
            x.astype(compute), w_f.astype(compute),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )
        return y.astype(dtype)
    y = jax.lax.dot_general(
        x.astype(compute), w.astype(compute),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    return (y * scale).astype(dtype)


def quantize_kernel_int4(
    w2d: jnp.ndarray, tile_n: int, group_size: int = 0
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int4: ``[K, N]`` fp → ``(packed [K, N/2] int8, scale)``.

    ``group_size=0``: per-output-channel absmax/7, scale ``[N]``.
    ``group_size=g``: per-(K-group, channel) absmax/7, scale ``[K/g, N]``
    — the 4-bit quality recipe (g must divide K; 128 is the standard
    point AND the smallest group the Pallas decode kernel can serve at
    packed-width reads — Mosaic blocks K in multiples of 128; smaller
    groups decode via the XLA path). ``tile_n`` must match the serving
    call's tile (it bakes the slab order into the packing)."""
    w = jnp.asarray(w2d, jnp.float32)
    k, n = w.shape
    if group_size:
        if group_size < 1 or k % group_size:
            raise ValueError(
                f"group_size {group_size} must divide K={k}"
            )
        g = w.reshape(k // group_size, group_size, n)
        absmax = jnp.max(jnp.abs(g), axis=1)             # [K/g, N]
        scale = jnp.where(absmax > 0, absmax / 7.0, 1.0)
        nib = jnp.clip(
            jnp.round(g / scale[:, None, :]), -8, 7
        ).astype(jnp.int8).reshape(k, n)
        return pack_int4(nib, tile_n), scale.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w), axis=0)                 # [N]
    scale = jnp.where(absmax > 0, absmax / 7.0, 1.0)
    nib = jnp.clip(jnp.round(w / scale), -8, 7).astype(jnp.int8)
    return pack_int4(nib, tile_n), scale.astype(jnp.float32)


def tile_for(n: int, k: int, shards: int = 1) -> int:
    """The tile the serving layer should bake for ``N`` output channels
    at contraction width ``K`` (0 = no conforming tile; the layer must
    stay int8). ``shards``: the tensor-parallel degree the packing must
    survive — the tile must divide the per-device channel count so a
    ``tensor``-axis shard of the packed/scale columns stays a valid
    slab packing on every device (any divisor of ``shards`` also serves
    correctly; a FINER split than packed for does not)."""
    return _grid_for(n, k, shards=shards)[0]
