"""Ring attention: sequence-parallel attention over the ICI ring.

Long-context strategy (SURVEY.md §5.7): q/k/v are sharded along the
sequence axis of the mesh; each device holds a [B, S/n, H, D] shard. The
algorithm rotates the K/V shards around the ring with ``lax.ppermute``
(ICI neighbor exchange) for n steps; every device accumulates blockwise
online-softmax partial results for its local queries against each visiting
K/V shard, normalizing once after the last step. Communication overlaps
compute because ppermute of step i+1's shard is issued while step i's
blockwise accumulation runs (XLA schedules the overlap; the per-step
compute is itself a lax.scan over KV blocks).

Causal masking uses **global** positions: the visiting shard at step s on
device r originates from device (r - s) mod n, so its kv offset is known
statically per step.

The public entry :func:`ring_attention` wraps the per-shard body in
``shard_map`` over the mesh's sequence axis; :func:`ring_attention_sharded`
is the raw collective body for use inside an existing shard_map/pjit
(e.g. the Llama trainer).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from unionml_tpu.parallel import compat
from jax import lax

from unionml_tpu.ops.attention import NEG_INF, _blockwise_accumulate, _repeat_kv


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis: str = "sequence",
    causal: bool = False,
    block_size: int = 512,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Per-shard ring attention body (call inside shard_map).

    ``q, k, v``: local shards [B, S_local, H, D]; returns the local output
    shard. Requires every device's shard to have equal length.
    """
    n = compat.axis_size(axis)
    my_idx = lax.axis_index(axis)
    batch, s_local, num_q_heads, head_dim = q.shape
    # NOTE: GQA kv shards rotate un-repeated — _blockwise_accumulate expands
    # kv heads locally, so ppermute moves kv_heads/q_heads of the naive bytes
    scale_ = scale if scale is not None else head_dim**-0.5

    q_offset = my_idx * s_local

    out0 = jnp.zeros((batch, s_local, num_q_heads, head_dim), jnp.float32)
    m0 = jnp.full((batch, s_local, num_q_heads), NEG_INF, jnp.float32)
    l0 = jnp.zeros((batch, s_local, num_q_heads), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, s):
        out, m, l, k_cur, v_cur = carry
        # the shard visiting at step s came from device (my_idx - s) mod n
        kv_offset = ((my_idx - s) % n) * s_local
        # rotate while computing: XLA overlaps the ppermute with the scan
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        out, m, l = _blockwise_accumulate(
            q, k_cur, v_cur,
            causal=causal, block_size=block_size, scale=scale_,
            q_offset=q_offset, kv_offset=kv_offset,
            acc=(out, m, l),
        )
        return (out, m, l, k_nxt, v_nxt), None

    (out, m, l, _, _), _ = lax.scan(
        step, (out0, m0, l0, k, v), jnp.arange(n)
    )
    return (out / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh,
    *,
    axis: str = "sequence",
    causal: bool = False,
    block_size: int = 512,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Ring attention over globally-shaped [B,S,H,D] tensors.

    Shards the sequence axis over ``mesh[axis]``, runs the ring, and
    returns the globally-shaped output (sharded the same way).
    """
    from unionml_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis, None, None)
    body = functools.partial(
        ring_attention_sharded, axis=axis, causal=causal,
        block_size=block_size, scale=scale,
    )
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


# --------------------------------------------------------------------- #
# ring flash: the Pallas flash kernel as the per-step local compute
# --------------------------------------------------------------------- #
#
# SURVEY.md §5.7 calls for "ring attention as a Pallas kernel with
# ppermute-style KV rotation over ICI". The blockwise body above is pure
# XLA; here each ring step instead runs the VMEM-tiled flash kernel
# (ops/flash_attention.py) on (local q, visiting kv) and the per-step
# partial outputs are merged by their logsumexp:
#
#   lse   = logaddexp(lse_a, lse_b)
#   out   = out_a * exp(lse_a - lse) + out_b * exp(lse_b - lse)
#
# Because shards are contiguous sequence chunks, the visiting shard is
# either entirely in the past (full attention), the diagonal (standard
# causal, q_len == kv_len), or entirely in the future (skipped) — a
# 3-way lax.switch keeps the kernel's causal flag static.
#
# The backward is the FlashAttention-2 scheme ring-ified: the saved
# GLOBAL lse and delta = rowsum(dO * O) drive the per-step _flash_bwd
# kernels; dq accumulates locally while dk/dv accumulate on buffers that
# rotate WITH their kv shards, arriving home after the full loop.

from unionml_tpu.ops.flash_attention import (  # noqa: E402
    _flash_bwd_bhsd,
    _flash_fwd_bhsd,
    _from_bhsd,
    _interpret,
    _to_bhsd,
)


def _merge_partial(acc_out, acc_lse, out_i, lse_i):
    """Merge NORMALIZED partials by logsumexp: the invariant is
    ``acc_out = sum_j out_j * exp(lse_j - acc_lse)`` — each update
    reweights both sides by their share of the new total.
    [BH, S, D] fp32 / [BH, S, 1] fp32."""
    both_empty = jnp.logical_and(acc_lse <= NEG_INF / 2, lse_i <= NEG_INF / 2)
    m = jnp.maximum(acc_lse, lse_i)
    w_acc = jnp.exp(acc_lse - m)
    w_i = jnp.exp(lse_i - m)
    total = jnp.maximum(w_acc + w_i, 1e-30)
    out = (acc_out * w_acc + out_i * w_i) / total
    lse = jnp.where(both_empty, NEG_INF, m + jnp.log(total))
    return out, lse


def _ring_flash_fwd_steps(q_bhsd, k0, v0, *, axis, causal, scale, block_q, block_kv,
                          num_heads):
    """Run the ring. ``q_bhsd``: [B*H, S_loc, D]; ``k0, v0``: 4D
    [B, S_loc, KVH, D] (rotate unrepeated). Returns (out fp32, lse)."""
    n = compat.axis_size(axis)
    my_idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    interpret = _interpret()
    bh, s_loc, d = q_bhsd.shape

    def flash(kv, causal_flag):
        k_r = _to_bhsd(_repeat_kv(kv[0], num_heads))
        v_r = _to_bhsd(_repeat_kv(kv[1], num_heads))
        return _flash_fwd_bhsd(
            q_bhsd, k_r, v_r, causal=causal_flag, scale=scale,
            block_q=block_q, block_kv=block_kv, interpret=interpret,
        )

    def step(carry, s):
        out, lse, k_cur, v_cur = carry
        kv_src = (my_idx - s) % n
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        if causal:
            rel = jnp.where(kv_src < my_idx, 0, jnp.where(kv_src == my_idx, 1, 2))
            out_i, lse_i = lax.switch(
                rel,
                [
                    lambda kv: flash(kv, False),
                    lambda kv: flash(kv, True),
                    lambda kv: (
                        jnp.zeros((bh, s_loc, d), q_bhsd.dtype),
                        jnp.full((bh, s_loc, 1), NEG_INF, jnp.float32),
                    ),
                ],
                (k_cur, v_cur),
            )
        else:
            out_i, lse_i = flash((k_cur, v_cur), False)
        out, lse = _merge_partial(out, lse, out_i.astype(jnp.float32), lse_i)
        return (out, lse, k_nxt, v_nxt), None

    out0 = jnp.zeros((bh, s_loc, d), jnp.float32)
    lse0 = jnp.full((bh, s_loc, 1), NEG_INF, jnp.float32)
    (out, lse, _, _), _ = lax.scan(step, (out0, lse0, k0, v0), jnp.arange(n))
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis, causal, scale, block_q, block_kv):
    out, _ = _ring_flash_fwd(q, k, v, axis, causal, scale, block_q, block_kv)
    return out


def _ring_flash_fwd(q, k, v, axis, causal, scale, block_q, block_kv):
    num_heads = q.shape[2]
    q_bhsd = _to_bhsd(q)
    out, lse = _ring_flash_fwd_steps(
        q_bhsd, k, v, axis=axis, causal=causal, scale=scale,
        block_q=block_q, block_kv=block_kv, num_heads=num_heads,
    )
    out = out.astype(q.dtype)
    b = q.shape[0]
    return _from_bhsd(out, b, num_heads), (q, k, v, out, lse)


def _ring_flash_bwd(axis, causal, scale, block_q, block_kv, residuals, g):
    q, k, v, out_bhsd, lse = residuals
    b, s_loc, h, d = q.shape
    kv_heads = k.shape[2]
    n = compat.axis_size(axis)
    my_idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    interpret = _interpret()

    q_bhsd = _to_bhsd(q)
    do = _to_bhsd(g)
    delta = jnp.sum(
        do.astype(jnp.float32) * out_bhsd.astype(jnp.float32), axis=-1, keepdims=True
    )

    def flash_bwd(kv, causal_flag):
        """Returns (dq_i [BH,S,D], dk_i, dv_i 4D [B,S,KVH,D])."""
        k_r = _to_bhsd(_repeat_kv(kv[0], h))
        v_r = _to_bhsd(_repeat_kv(kv[1], h))
        dq_i, dk_r, dv_r = _flash_bwd_bhsd(
            q_bhsd, k_r, v_r, do, lse, delta,
            causal=causal_flag, scale=scale, block_q=block_q, block_kv=block_kv,
            interpret=interpret,
        )
        dk_i = _from_bhsd(dk_r, b, h)
        dv_i = _from_bhsd(dv_r, b, h)
        if kv_heads != h:
            group = h // kv_heads
            dk_i = dk_i.reshape(b, s_loc, kv_heads, group, d).sum(3)
            dv_i = dv_i.reshape(b, s_loc, kv_heads, group, d).sum(3)
        return dq_i, dk_i, dv_i

    def step(carry, s):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        kv_src = (my_idx - s) % n
        # issue the next kv rotation BEFORE the backward kernels (same as
        # the forward) so the ICI transfer overlaps the Pallas compute;
        # only dk/dv depend on this step's accumulation
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        if causal:
            rel = jnp.where(kv_src < my_idx, 0, jnp.where(kv_src == my_idx, 1, 2))
            dq_i, dk_i, dv_i = lax.switch(
                rel,
                [
                    lambda kv: flash_bwd(kv, False),
                    lambda kv: flash_bwd(kv, True),
                    lambda kv: (
                        jnp.zeros_like(q_bhsd),
                        jnp.zeros((b, s_loc, kv_heads, d), k.dtype),
                        jnp.zeros((b, s_loc, kv_heads, d), v.dtype),
                    ),
                ],
                (k_cur, v_cur),
            )
        else:
            dq_i, dk_i, dv_i = flash_bwd((k_cur, v_cur), False)
        dq = dq + dq_i.astype(dq.dtype)
        dk_cur = dk_cur + dk_i.astype(dk_cur.dtype)
        dv_cur = dv_cur + dv_i.astype(dv_cur.dtype)
        # gradient accumulators rotate with their kv shards: after the
        # full loop both are back at the shard's home device
        dk_nxt = lax.ppermute(dk_cur, axis, perm)
        dv_nxt = lax.ppermute(dv_cur, axis, perm)
        return (dq, k_nxt, v_nxt, dk_nxt, dv_nxt), None

    dq0 = jnp.zeros_like(q_bhsd, jnp.float32)
    dk0 = jnp.zeros((b, s_loc, kv_heads, d), jnp.float32)
    dv0 = jnp.zeros((b, s_loc, kv_heads, d), jnp.float32)
    (dq, _, _, dk, dv), _ = lax.scan(
        step, (dq0, k, v, dk0, dv0), jnp.arange(n)
    )
    return (
        _from_bhsd(dq, b, h).astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
    )


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis: str = "sequence",
    causal: bool = False,
    block_size: int = 512,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Per-shard ring attention with Pallas flash local compute.

    Call inside shard_map with ``axis`` bound; ``q, k, v`` are local
    [B, S_local, H, D] shards (kv may have fewer GQA heads). Differentiable
    end to end (ring-level custom VJP; FlashAttention-2 backward kernels
    per step).
    """
    scale_ = scale if scale is not None else q.shape[-1] ** -0.5
    return _ring_flash(q, k, v, axis, causal, scale_, block_size, block_size)


def ring_flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh,
    *,
    axis: str = "sequence",
    causal: bool = False,
    block_size: int = 512,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Ring flash attention over globally-shaped [B,S,H,D] tensors."""
    from unionml_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis, None, None)
    body = functools.partial(
        ring_flash_attention_sharded, axis=axis, causal=causal,
        block_size=block_size, scale=scale,
    )
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
