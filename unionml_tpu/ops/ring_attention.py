"""Ring attention: sequence-parallel attention over the ICI ring.

Long-context strategy (SURVEY.md §5.7): q/k/v are sharded along the
sequence axis of the mesh; each device holds a [B, S/n, H, D] shard. The
algorithm rotates the K/V shards around the ring with ``lax.ppermute``
(ICI neighbor exchange) for n steps; every device accumulates blockwise
online-softmax partial results for its local queries against each visiting
K/V shard, normalizing once after the last step. Communication overlaps
compute because ppermute of step i+1's shard is issued while step i's
blockwise accumulation runs (XLA schedules the overlap; the per-step
compute is itself a lax.scan over KV blocks).

Causal masking uses **global** positions: the visiting shard at step s on
device r originates from device (r - s) mod n, so its kv offset is known
statically per step.

The public entry :func:`ring_attention` wraps the per-shard body in
``shard_map`` over the mesh's sequence axis; :func:`ring_attention_sharded`
is the raw collective body for use inside an existing shard_map/pjit
(e.g. the Llama trainer).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from unionml_tpu.ops.attention import NEG_INF, _blockwise_accumulate, _repeat_kv


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis: str = "sequence",
    causal: bool = False,
    block_size: int = 512,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Per-shard ring attention body (call inside shard_map).

    ``q, k, v``: local shards [B, S_local, H, D]; returns the local output
    shard. Requires every device's shard to have equal length.
    """
    n = lax.axis_size(axis)
    my_idx = lax.axis_index(axis)
    batch, s_local, num_q_heads, head_dim = q.shape
    # NOTE: GQA kv shards rotate un-repeated — _blockwise_accumulate expands
    # kv heads locally, so ppermute moves kv_heads/q_heads of the naive bytes
    scale_ = scale if scale is not None else head_dim**-0.5

    q_offset = my_idx * s_local

    out0 = jnp.zeros((batch, s_local, num_q_heads, head_dim), jnp.float32)
    m0 = jnp.full((batch, s_local, num_q_heads), NEG_INF, jnp.float32)
    l0 = jnp.zeros((batch, s_local, num_q_heads), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, s):
        out, m, l, k_cur, v_cur = carry
        # the shard visiting at step s came from device (my_idx - s) mod n
        kv_offset = ((my_idx - s) % n) * s_local
        # rotate while computing: XLA overlaps the ppermute with the scan
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        out, m, l = _blockwise_accumulate(
            q, k_cur, v_cur,
            causal=causal, block_size=block_size, scale=scale_,
            q_offset=q_offset, kv_offset=kv_offset,
            acc=(out, m, l),
        )
        return (out, m, l, k_nxt, v_nxt), None

    (out, m, l, _, _), _ = lax.scan(
        step, (out0, m0, l0, k, v), jnp.arange(n)
    )
    return (out / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh,
    *,
    axis: str = "sequence",
    causal: bool = False,
    block_size: int = 512,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Ring attention over globally-shaped [B,S,H,D] tensors.

    Shards the sequence axis over ``mesh[axis]``, runs the ring, and
    returns the globally-shaped output (sharded the same way).
    """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis, None, None)
    body = functools.partial(
        ring_attention_sharded, axis=axis, causal=causal,
        block_size=block_size, scale=scale,
    )
    return shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
