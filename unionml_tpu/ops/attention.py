"""Multi-head attention: XLA reference + memory-efficient blockwise form.

Convention (all attention ops in this package): tensors are
``[batch, seq, heads, head_dim]`` ("BSHD"). GQA is supported everywhere —
``k``/``v`` may have fewer heads than ``q`` as long as the count divides.

- :func:`mha_reference` materializes the full [S, S] score matrix; XLA
  fuses the softmax chain well, and on TPU this is the fastest choice for
  short/medium sequences that fit HBM.
- :func:`blockwise_attention` never materializes scores: a ``lax.scan``
  over KV blocks with an **online softmax** (running max + normalizer),
  trading FLOPs for O(S·block) memory — the long-context building block
  that ring attention reuses per-shard.
- :func:`attention` dispatches between implementations.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _repeat_kv(k: jnp.ndarray, num_q_heads: int) -> jnp.ndarray:
    """GQA: repeat kv heads to match q heads."""
    num_kv_heads = k.shape[2]
    if num_kv_heads == num_q_heads:
        return k
    if num_q_heads % num_kv_heads:
        raise ValueError(f"q heads {num_q_heads} must be a multiple of kv heads {num_kv_heads}")
    return jnp.repeat(k, num_q_heads // num_kv_heads, axis=2)


def _causal_mask(q_len: int, kv_len: int, q_offset: int = 0, kv_offset: int = 0) -> jnp.ndarray:
    """[q_len, kv_len] bool mask, True where attention is allowed."""
    q_pos = q_offset + jnp.arange(q_len)[:, None]
    kv_pos = kv_offset + jnp.arange(kv_len)[None, :]
    return q_pos >= kv_pos


def mha_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    bias: Optional[jnp.ndarray] = None,
    segment_ids: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Full-score multi-head attention ([B,S,H,D] in/out).

    ``bias`` broadcasts against [B, H, Sq, Skv]; ``segment_ids`` ([B, S])
    restricts attention within equal segments (packed sequences).
    """
    *_, num_q_heads, head_dim = q.shape
    k = _repeat_kv(k, num_q_heads)
    v = _repeat_kv(v, num_q_heads)
    scale = scale if scale is not None else head_dim**-0.5

    # [B,H,Sq,Skv] scores on the MXU in fp32 for numerical stability
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if bias is not None:
        scores = scores + bias
    if causal:
        # bottom-right alignment: with q_len < kv_len the queries are the
        # LAST q_len positions (KV-cache decode), so offset q, not kv
        mask = _causal_mask(q.shape[1], k.shape[1], q_offset=k.shape[1] - q.shape[1])
        scores = jnp.where(mask[None, None], scores, NEG_INF)
    if segment_ids is not None:
        seg_mask = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        scores = jnp.where(jnp.swapaxes(seg_mask, -1, -2), scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def _grouped_cache_attention(
    q,
    k,
    v,
    *,
    k_scale=None,
    v_scale=None,
    bias=None,
    scale=None,
    block_threshold: int = 2048,
):
    """Shared engine for cached-decode attention (bf16 or int8 KV).

    ``k``/``v``: [B, S, Hk, D] (bf16, or int8 with ``k_scale``/``v_scale``
    fp32 [B, S, Hk] per-(position, head) dequant scales). Three design
    rules, each from a measured failure (BASELINE.md round 3):

    - **No GQA repeat.** The group dim folds into the einsums (q reshaped
      to [B, Sq, Hk, G, D]) so the cache is read at its own byte size; a
      materialized repeat costs G x the cache traffic per decode step
      (4x at the 8B geometry).
    - **No dequantized copy.** int8 scales ride the small tensors —
      ``k_scale`` multiplies the scores, ``v_scale`` multiplies the
      softmax weights — so cache HBM reads stay int8.
    - **Bounded VMEM, no cache copies.** Above ``block_threshold`` keys
      the full-row softmax (f32[B, H, S] > 16 MB scoped VMEM at 8k) is
      replaced by an online-softmax ``lax.scan`` over block INDICES with
      ``dynamic_slice`` into the cache — passing cache blocks as scan
      operands would materialize a transposed copy of the whole cache
      every step (measured: 4 GB of HLO-temp copies at 8B/8k, an HBM
      OOM). A non-dividing tail slab is merged after the scan, so the
      cache is never padded (padding is a full copy too).

    ``bias`` must broadcast over heads (head dim 1) — every cache caller
    satisfies this. Output [B, Sq, Hq, D] in ``q.dtype``, equal to the
    materialized form up to float reduction order.
    """
    batch, q_len, num_q_heads, head_dim = q.shape
    num_kv_heads = k.shape[2]
    if num_q_heads % num_kv_heads:
        raise ValueError(
            f"q heads {num_q_heads} must be a multiple of kv heads {num_kv_heads}"
        )
    group = num_q_heads // num_kv_heads
    if bias is not None and bias.shape[1] != 1:
        raise ValueError(
            f"bias head dim must be 1 (broadcast over heads), got {bias.shape}"
        )
    scale = scale if scale is not None else head_dim**-0.5
    kv_len = k.shape[1]
    # [B, Sq, Hk, G, D]: contiguous head groups share a kv head (the
    # jnp.repeat layout _repeat_kv would produce)
    qg = q.reshape(batch, q_len, num_kv_heads, group, head_dim)

    def scores_for(k_c, ks_c, bias_c):
        """k-scale-folded scores for one key slab: [B, Hk, G, Q, K]."""
        s = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qg, k_c.astype(q.dtype),
            preferred_element_type=jnp.float32,
        ) * scale
        if ks_c is not None:
            s = s * jnp.transpose(ks_c, (0, 2, 1))[:, :, None, None, :]
        if bias_c is not None:
            s = s + bias_c[:, :, None]  # [B,1,Q,K] -> [B,1,1,Q,K]
        return s

    def weighted_values(w, v_c, vs_c):
        if vs_c is not None:
            w = w * jnp.transpose(vs_c, (0, 2, 1))[:, :, None, None, :]
        return jnp.einsum(
            "bhgqk,bkhd->bqhgd", w.astype(q.dtype), v_c.astype(q.dtype),
            preferred_element_type=jnp.float32,
        )

    if kv_len <= block_threshold:
        weights = jax.nn.softmax(scores_for(k, k_scale, bias), axis=-1)
        out = weighted_values(weights, v, v_scale)
        return out.reshape(batch, q_len, num_q_heads, head_dim).astype(q.dtype)

    block = block_threshold
    n_full, tail = divmod(kv_len, block)

    def slab(x, start, size, axis=1):
        return (
            None
            if x is None
            else jax.lax.dynamic_slice_in_dim(x, start, size, axis=axis)
        )

    def merge(carry, start, size):
        """Online-softmax update with the [start, start+size) key slab."""
        m, l, acc = carry
        s = scores_for(
            slab(k, start, size), slab(k_scale, start, size),
            slab(bias, start, size, axis=3),
        )
        m_new = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l = l * corr + p.sum(axis=-1)
        acc = acc * jnp.moveaxis(corr, 3, 1)[..., None] + weighted_values(
            p, slab(v, start, size), slab(v_scale, start, size)
        )
        return m_new, l, acc

    stat = (batch, num_kv_heads, group, q_len)
    carry = (
        jnp.full(stat, NEG_INF, jnp.float32),
        jnp.zeros(stat, jnp.float32),
        jnp.zeros((batch, q_len, num_kv_heads, group, head_dim), jnp.float32),
    )
    if n_full:
        carry, _ = jax.lax.scan(
            lambda c, start: (merge(c, start, block), None),
            carry,
            jnp.arange(n_full, dtype=jnp.int32) * block,
        )
    if tail:
        carry = merge(carry, n_full * block, tail)
    m, l, acc = carry
    out = acc / jnp.moveaxis(l, 3, 1)[..., None]
    return out.reshape(batch, q_len, num_q_heads, head_dim).astype(q.dtype)


def cached_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    bias: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    block_threshold: int = 2048,
) -> jnp.ndarray:
    """bf16 KV-cache decode attention: grouped GQA (no cache repeat),
    VMEM-bounded block scan at long context. See
    :func:`_grouped_cache_attention`."""
    return _grouped_cache_attention(
        q, k, v, bias=bias, scale=scale, block_threshold=block_threshold
    )


def quantized_cache_attention(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    v_q: jnp.ndarray,
    k_s: jnp.ndarray,
    v_s: jnp.ndarray,
    *,
    bias: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    block_threshold: int = 2048,
) -> jnp.ndarray:
    """int8 KV-cache decode attention, dequant scales folded into the
    attention math (never a dequantized cache copy). See
    :func:`_grouped_cache_attention`."""
    return _grouped_cache_attention(
        q, k_q, v_q, k_scale=k_s, v_scale=v_s, bias=bias, scale=scale,
        block_threshold=block_threshold,
    )


def blockwise_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    block_size: int = 512,
    scale: Optional[float] = None,
    q_offset: int = 0,
    kv_offset: int = 0,
) -> jnp.ndarray:
    """Online-softmax attention scanned over KV blocks ([B,S,H,D] in/out).

    Memory is O(Sq·block_size) instead of O(Sq·Skv). ``q_offset`` /
    ``kv_offset`` give the global positions of the local q/kv shards so
    ring attention can reuse this per rotation step with correct causal
    masking. With default (zero) offsets and ``q_len != kv_len``, causal
    masking is bottom-right aligned (queries are the last ``q_len``
    positions — the KV-cache decode convention, matching mha_reference).
    """
    if causal and q_offset == 0 and kv_offset == 0:
        q_offset = k.shape[1] - q.shape[1]
    out, _, _ = _blockwise_accumulate(
        q, k, v, causal=causal, block_size=block_size, scale=scale,
        q_offset=q_offset, kv_offset=kv_offset,
    )
    return out.astype(q.dtype)


def _blockwise_accumulate(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool,
    block_size: int,
    scale: Optional[float],
    q_offset: int = 0,
    kv_offset: int = 0,
    acc: Optional[tuple] = None,
):
    """Scan KV blocks, returning ``(out, running_max, normalizer)``.

    ``acc = (out_unnormalized, m, l)`` lets callers (ring attention) chain
    accumulation across KV shards and normalize once at the end.
    """
    batch, q_len, num_q_heads, head_dim = q.shape
    kv_len = k.shape[1]
    k = _repeat_kv(k, num_q_heads)
    v = _repeat_kv(v, num_q_heads)
    scale = scale if scale is not None else head_dim**-0.5

    block_size = min(block_size, kv_len)
    num_blocks = -(-kv_len // block_size)
    pad = num_blocks * block_size - kv_len
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))

    # [num_blocks, B, block, H, D] for the scan carry-free xs
    k_blocks = k.reshape(batch, num_blocks, block_size, num_q_heads, head_dim).swapaxes(0, 1)
    v_blocks = v.reshape(batch, num_blocks, block_size, num_q_heads, head_dim).swapaxes(0, 1)

    q_pos = q_offset + jnp.arange(q_len)
    qf = q.astype(jnp.float32)

    if acc is None:
        out0 = jnp.zeros((batch, q_len, num_q_heads, head_dim), jnp.float32)
        m0 = jnp.full((batch, q_len, num_q_heads), NEG_INF, jnp.float32)
        l0 = jnp.zeros((batch, q_len, num_q_heads), jnp.float32)
    else:
        out0, m0, l0 = acc

    def body(carry, inputs):
        out_acc, m_acc, l_acc = carry
        blk_idx, k_blk, v_blk = inputs
        kv_pos = kv_offset + blk_idx * block_size + jnp.arange(block_size)

        # [B,H,Q,Bk] block scores in fp32
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32),
                       preferred_element_type=jnp.float32) * scale
        valid = kv_pos < (kv_offset + kv_len)
        mask = jnp.broadcast_to(valid[None, :], (q_len, block_size))
        if causal:
            mask = mask & (q_pos[:, None] >= kv_pos[None, :])
        s = jnp.where(mask[None, None], s, NEG_INF)

        m_blk = jnp.max(s, axis=-1)                      # [B,H,Q]
        m_new = jnp.maximum(m_acc, m_blk.transpose(0, 2, 1))  # [B,Q,H]
        # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
        m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(s - m_safe.transpose(0, 2, 1)[..., None])
        p = jnp.where(mask[None, None], p, 0.0)
        corr = jnp.exp(jnp.where(m_acc == NEG_INF, NEG_INF, m_acc - m_safe))
        l_new = l_acc * corr + jnp.sum(p, axis=-1).transpose(0, 2, 1)
        out_new = out_acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        return (out_new, m_new, l_new), None

    (out, m, l), _ = jax.lax.scan(
        body, (out0, m0, l0), (jnp.arange(num_blocks), k_blocks, v_blocks)
    )
    if acc is not None:
        return out, m, l
    return out / jnp.maximum(l, 1e-30)[..., None], m, l


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    impl: str = "xla",
    block_size: int = 512,
    **kwargs,
) -> jnp.ndarray:
    """Dispatch between attention implementations.

    impl: ``"xla"`` (full scores), ``"blockwise"`` (O(S·block) memory),
    ``"flash"`` (Pallas TPU kernel, long sequences), ``"fused"`` (Pallas
    one-program-per-batch kernel, fastest for short sequences), or
    ``"auto"`` — fused up to the measured v5e crossover (~1k tokens,
    where the single-tile score matrix stops fitting VMEM comfortably),
    flash beyond it.
    """
    if impl == "auto":
        from unionml_tpu.ops.fused_attention import MAX_FUSED_SEQ

        impl = (
            "fused"
            if q.shape[1] <= MAX_FUSED_SEQ and k.shape[1] == q.shape[1]
            else "flash"
        )
    if impl == "xla":
        return mha_reference(q, k, v, causal=causal, **kwargs)
    if impl == "blockwise":
        return blockwise_attention(q, k, v, causal=causal, block_size=block_size, **kwargs)
    if impl == "flash":
        from unionml_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, **kwargs)
    if impl == "fused":
        from unionml_tpu.ops.fused_attention import fused_attention

        return fused_attention(q, k, v, causal=causal, **kwargs)
    raise ValueError(
        f"unknown attention impl {impl!r}; use auto|xla|blockwise|flash|fused"
    )
