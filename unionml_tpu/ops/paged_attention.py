"""Paged-attention decode: block-table KV gather with online softmax.

The decode companion to the engine's block-paged KV pool
(:mod:`unionml_tpu.serving.kv_pool`): per layer the KV cache is a
global pool ``[num_blocks, block_size, kv_heads, head_dim]`` and each
resident slot owns an int32 block table mapping logical rows to pool
blocks. One decode step attends each slot's single query against its
table-addressed blocks — the PagedAttention formulation (Kwon et al.,
SOSP 2023) on the TPU layout this repo already uses for its flash
kernels.

Two implementations behind one dispatcher:

- :func:`paged_attention_reference` — pure JAX: ``jnp.take`` gathers
  the table's blocks into a contiguous ``[B, W*block, Hk, D]`` view and
  runs the SAME masked math as the contiguous engine path
  (:func:`~unionml_tpu.ops.attention.cached_attention` /
  ``quantized_cache_attention``). Columns past a row's length carry a
  ``-1e30`` bias, so their softmax weights underflow to exact zeros and
  the outputs are **bit-identical** to the contiguous cache path on the
  same values — the CPU/tier-1 parity anchor every paged-engine test
  asserts against.
- the Pallas kernel (``impl="pallas"``) — grid ``(batch, table_width)``
  with the block dimension innermost: the block table rides in as a
  **scalar-prefetch** operand so each grid step's BlockSpec index map
  selects the pool block to DMA (no gathered copy of the cache is ever
  materialized — the entire point: decode reads exactly the blocks a
  sequence owns). fp32 online-softmax accumulators (running max /
  normalizer / weighted sum) live in VMEM scratch and carry across the
  block iterations, the same scheme as
  :mod:`~unionml_tpu.ops.flash_attention`; blocks entirely past a
  row's length are predicated out with ``pl.when``. GQA reads the pool
  at kv-head width (no head repeat); int8 KV pools fold their
  per-(row, head) dequant scales into the score/weight math in-kernel
  (never a dequantized pool copy) — the same numerics contract as the
  existing kernels: fp32 softmax statistics, MXU matmuls in the input
  dtype with fp32 accumulation, outputs equal to the reference up to
  float reduction order.

``impl="auto"`` picks the kernel on TPU and the reference elsewhere
(CPU tests run the kernel in interpreter mode only when asked).
Block-size tuning is data-driven via the paged leg of
``benchmarks/attn_kernels.py``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30

__all__ = ["paged_attention", "paged_attention_reference"]


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _check_shapes(q, k, v, block_table, lengths, k_scale, v_scale):
    if q.ndim != 3:
        raise ValueError(f"q must be [batch, q_heads, head_dim], got {q.shape}")
    if k.ndim != 4 or v.shape != k.shape:
        raise ValueError(
            "k/v pools must be [num_blocks, block_size, kv_heads, "
            f"head_dim], got {k.shape} / {v.shape}"
        )
    if block_table.ndim != 2 or block_table.shape[0] != q.shape[0]:
        raise ValueError(
            f"block_table must be [batch, table_width], got "
            f"{block_table.shape} for batch {q.shape[0]}"
        )
    if lengths.shape != (q.shape[0],):
        raise ValueError(
            f"lengths must be [batch], got {lengths.shape}"
        )
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale come together (int8 pools)")
    if q.shape[1] % k.shape[2]:
        raise ValueError(
            f"q heads {q.shape[1]} must be a multiple of kv heads "
            f"{k.shape[2]}"
        )


def paged_attention_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    block_table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Pure-JAX paged decode attention (the parity/CPU path).

    ``jnp.take`` flattens the block table into a contiguous per-row KV
    view, then runs the exact contiguous-cache decode math
    (:func:`~unionml_tpu.ops.attention._grouped_cache_attention` with
    the same ``-1e30`` bias construction the engine's contiguous path
    uses) — masked tail columns contribute exact zeros, so outputs are
    bit-identical to a contiguous cache holding the same rows.

    Shapes: ``q`` [B, Hq, D]; ``k``/``v`` [N, block, Hk, D] (int8 with
    fp32 ``k_scale``/``v_scale`` [N, block, Hk]); ``block_table``
    [B, W] int32; ``lengths`` [B] int32 (visible rows per batch row —
    a decode step passes ``fill + 1`` so the just-written row sees
    itself). Returns [B, Hq, D] in ``q.dtype``.
    """
    from unionml_tpu.ops.attention import _grouped_cache_attention

    _check_shapes(q, k, v, block_table, lengths, k_scale, v_scale)
    batch, w = block_table.shape
    block = k.shape[1]
    flat = block_table.reshape(-1)

    def gather(pool):
        g = jnp.take(pool, flat, axis=0)          # [B*W, block, ...]
        return g.reshape((batch, w * block) + pool.shape[2:])

    gk, gv = gather(k), gather(v)
    gks = None if k_scale is None else gather(k_scale)
    gvs = None if v_scale is None else gather(v_scale)
    # the engine's contiguous decode bias, verbatim: kv slot j visible
    # to the (single) query iff j <= q_pos, with q_pos = lengths - 1
    kv_pos = jnp.arange(w * block)[None, :]
    visible = kv_pos[None] <= (lengths.astype(jnp.int32) - 1)[:, None, None]
    bias = jnp.where(visible, 0.0, NEG_INF)[:, None]   # [B, 1, 1, W*block]
    out = _grouped_cache_attention(
        q[:, None], gk, gv, k_scale=gks, v_scale=gvs, bias=bias, scale=scale,
    )
    return out[:, 0]


def _paged_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                  scale, block, kv_heads, group, num_blocks, quantized):
    if quantized:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        ks_ref = vs_ref = None
        o_ref, acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    w = pl.program_id(1)

    @pl.when(w == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    length = len_ref[b]
    # skip blocks entirely past this row's visible rows (pl.when: no
    # MXU work issued; the DMA fetched the trash block the host parks
    # out-of-range table entries on)
    run = w * block < length

    @pl.when(run)
    def _compute():
        q = q_ref[0]                               # [Hq, D] input dtype
        k = k_ref[0]                               # [block, Hk, D]
        v = v_ref[0]
        pos = w * block + jax.lax.broadcasted_iota(jnp.int32, (1, block), 1)
        valid = pos < length                       # [1, block]
        # kv heads unrolled (static, small): each group of q heads
        # shares one kv head's block tile — the no-repeat GQA read
        for h in range(kv_heads):
            rows = slice(h * group, (h + 1) * group)
            kh = k[:, h, :].astype(q.dtype)        # [block, D]
            s = jax.lax.dot_general(
                q[rows], kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                              # [G, block] fp32
            if quantized:
                # int8 pool: per-(row, head) dequant scale folds into
                # the scores (k) and softmax weights (v) — the
                # _grouped_cache_attention contract, in-kernel
                s = s * ks_ref[0][:, h][None, :]
            s = jnp.where(valid, s, NEG_INF)
            m_prev = m_ref[rows]                   # [G, 1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
            p = jnp.where(valid, jnp.exp(s - m_safe), 0.0)
            corr = jnp.exp(
                jnp.where(m_prev == NEG_INF, NEG_INF, m_prev - m_safe)
            )
            # the normalizer sums the UNSCALED softmax weights; the
            # v dequant scale rides only the weighted-value matmul
            # (the _grouped_cache_attention contract)
            l_ref[rows] = l_ref[rows] * corr + jnp.sum(
                p, axis=-1, keepdims=True
            )
            if quantized:
                p = p * vs_ref[0][:, h][None, :]
            # zero invalid value rows: 0-weight x garbage must stay 0
            vh = jnp.where(
                valid.reshape(block, 1), v[:, h, :].astype(q.dtype), 0
            )
            acc_ref[rows] = acc_ref[rows] * corr + jax.lax.dot_general(
                p.astype(q.dtype), vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[rows] = m_new

    @pl.when(w == num_blocks - 1)
    def _finalize():
        o_ref[0] = (
            acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
        ).astype(o_ref.dtype)


def _paged_pallas(q, k, v, block_table, lengths, *, k_scale, v_scale,
                  scale, interpret):
    from jax.experimental.pallas import tpu as pltpu

    batch, q_heads, head_dim = q.shape
    num_pool_blocks, block, kv_heads, _ = k.shape
    w = block_table.shape[1]
    group = q_heads // kv_heads
    quantized = k_scale is not None

    def kv_map(b, wi, table, lens):
        return (table[b, wi], 0, 0, 0)

    def scale_map(b, wi, table, lens):
        return (table[b, wi], 0, 0)

    def q_map(b, wi, table, lens):
        return (b, 0, 0)

    in_specs = [
        pl.BlockSpec((1, q_heads, head_dim), q_map),
        pl.BlockSpec((1, block, kv_heads, head_dim), kv_map),
        pl.BlockSpec((1, block, kv_heads, head_dim), kv_map),
    ]
    operands = [q, k, v]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, block, kv_heads), scale_map),
            pl.BlockSpec((1, block, kv_heads), scale_map),
        ]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, w),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, q_heads, head_dim), q_map),
        scratch_shapes=[
            pltpu.VMEM((q_heads, head_dim), jnp.float32),
            pltpu.VMEM((q_heads, 1), jnp.float32),
            pltpu.VMEM((q_heads, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_kernel,
        scale=scale,
        block=block,
        kv_heads=kv_heads,
        group=group,
        num_blocks=w,
        quantized=quantized,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch, q_heads, head_dim), q.dtype),
        interpret=interpret,
    )(
        block_table.astype(jnp.int32), lengths.astype(jnp.int32), *operands
    )


def paged_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    block_table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Single-step decode attention over a block-paged KV pool.

    Shapes: ``q`` [B, Hq, D] (one query per row — the decode step);
    ``k``/``v`` [num_blocks, block, Hk, D] pools (bf16, or int8 with
    fp32 ``k_scale``/``v_scale`` [num_blocks, block, Hk]);
    ``block_table`` [B, W] int32 (entries past a row's coverage point
    at the trash block); ``lengths`` [B] int32 visible rows. Returns
    [B, Hq, D] in ``q.dtype``.

    ``impl``: ``"reference"`` (pure JAX gather — bit-identical to the
    contiguous cache path, the tier-1/CPU anchor), ``"pallas"`` (the
    scalar-prefetch kernel; interpreter mode off-TPU), or ``"auto"``
    (pallas on TPU, reference elsewhere).
    """
    _check_shapes(q, k, v, block_table, lengths, k_scale, v_scale)
    if impl == "auto":
        impl = "reference" if _interpret() else "pallas"
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if impl == "reference":
        return paged_attention_reference(
            q, k, v, block_table, lengths,
            k_scale=k_scale, v_scale=v_scale, scale=scale,
        )
    if impl != "pallas":
        raise ValueError(f"unknown paged attention impl {impl!r}")
    return _paged_pallas(
        q, k, v, block_table, lengths,
        k_scale=k_scale, v_scale=v_scale, scale=scale,
        interpret=_interpret(),
    )
