"""Pallas TPU fused multi-head attention for short sequences.

At ViT/BERT sequence lengths (a few hundred tokens) attention is
*overhead*-bound, not memory-bound: a flash-style kernel with one program
per (batch, head) pays the fixed per-program pipeline cost 768 times for
microseconds of MXU work each (measured on v5e: ~1.2 us/program floor —
more than the matmuls themselves). This kernel instead runs ONE program
per batch element — grid ``(B,)`` — and loops over heads inside the
program, with the full ``S x S`` fp32 score tile resident in VMEM (200 KB
at S=224; use :mod:`unionml_tpu.ops.flash_attention` beyond ~1k tokens
where the tile stops fitting).

The backward is a single program per batch element too: with the whole
sequence in VMEM there is no cross-program accumulation, so softmax is
simply recomputed per head (no logsumexp residual) and dq/dk/dv are
written in one pass — five small matmuls per head, all fp32-accumulated
on the MXU via ``preferred_element_type``.

Layout: tensors are transposed to ``[B, H, S, D]`` outside the kernel so
each head slice ``ref[0, h]`` is a contiguous ``[S, D]`` tile (slicing a
leading block dim is free; slicing lanes is not).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from unionml_tpu.ops.flash_attention import NEG_INF, _interpret

# Above this sequence length the S x S fp32 score tile (plus operands)
# stops fitting comfortably in VMEM; callers should use flash_attention.
MAX_FUSED_SEQ = 1024

# Scores are computed in log2 space: log2(e) is folded into the q
# pre-scale outside the kernel, softmax uses exp2 (the VPU-native op exp
# lowers to anyway, minus the input multiply), and the backward folds the
# compensating ln(2) into its existing 1/z row factor.
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453


def _causal_mask(s_len):
    q_pos = jax.lax.broadcasted_iota(jnp.int32, (s_len, s_len), 0)
    kv_pos = jax.lax.broadcasted_iota(jnp.int32, (s_len, s_len), 1)
    return q_pos >= kv_pos


def _raw_scores(q, k, causal):
    """[S, S] fp32 scores; q is pre-scaled by the caller (the 1/sqrt(D)
    and log2(e) factors ride the [S, D] tensor outside the kernel — XLA
    fuses them into the projection — instead of an [S, S] multiply here).
    """
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )                                              # [S, S] fp32
    if causal:
        s = jnp.where(_causal_mask(s.shape[0]), s, NEG_INF)
    return s


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, causal, num_heads):
    # software-pipelined head loop: head h's QK^T (MXU) is emitted before
    # head h-1's softmax (VPU) + PV (MXU), so the two heads' independent
    # MXU/VPU work sits adjacent for the scheduler to overlap. (Writing
    # the softmax max/denominator out as [B, H, S] residuals for the
    # backward was tried and measured SLOWER — the lane-major stat writes
    # force in-kernel relayouts that cost more than the two [S, S]
    # reductions they save.)
    def finish(h, s):
        m = jnp.max(s, axis=-1, keepdims=True)
        e = jnp.exp2(s - m)                        # scores are log2-scaled
        z = jnp.sum(e, axis=-1, keepdims=True)
        o = jax.lax.dot_general(
            e.astype(v_ref.dtype), v_ref[0, h], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                          # [S, D] fp32
        o_ref[0, h] = (o / z).astype(o_ref.dtype)  # deferred normalization

    s_prev = _raw_scores(q_ref[0, 0], k_ref[0, 0], causal)
    for h in range(1, num_heads):
        s_next = _raw_scores(q_ref[0, h], k_ref[0, h], causal)
        finish(h - 1, s_prev)
        s_prev = s_next
    finish(num_heads - 1, s_prev)


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, dq_ref, dk_ref, dv_ref, *,
                causal, num_heads):
    # same software pipelining as the forward: head h's two big MXU
    # products (scores recompute + dp) are emitted before head h-1's
    # VPU-heavy softmax/ds work
    def start(h):
        s = _raw_scores(q_ref[0, h], k_ref[0, h], causal)
        dp = jax.lax.dot_general(
            do_ref[0, h], v_ref[0, h], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                          # [S, S]
        return s, dp

    def finish(h, s, dp):
        q = q_ref[0, h]
        do = do_ref[0, h]
        m = jnp.max(s, axis=-1, keepdims=True)
        e = jnp.exp2(s - m)                        # [S, S] fp32, log2 space
        z = jnp.sum(e, axis=-1, keepdims=True)
        # dv = p^T do = e^T (do / z): row-scale the [S, D] side, not p
        do_n = (do.astype(jnp.float32) / z).astype(do.dtype)
        dv_ref[0, h] = jax.lax.dot_general(
            e.astype(do.dtype), do_n, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dv_ref.dtype)
        # delta = sum(p * dp) = sum(do * o) — the flash-attention identity
        # (sum_j p_ij (do_i . v_j) = do_i . o_i) turns an [S, S] multiply
        # + reduce into an [S, D] one over the saved forward output
        delta = jnp.sum(
            do.astype(jnp.float32) * o_ref[0, h].astype(jnp.float32),
            axis=-1, keepdims=True,
        )
        # ds = p * (dp - delta) * ln2: the ln2 compensates d(exp2)/dx and
        # cancels against the caller's log2(e) pre-scale in dq/dk; q came
        # in pre-scaled so the chain rule's scale factor also lives outside
        ds = (e * (dp - delta) * (LN2 / z)).astype(q.dtype)
        dq_ref[0, h] = jax.lax.dot_general(
            ds, k_ref[0, h], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dq_ref.dtype)
        dk_ref[0, h] = jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ).astype(dk_ref.dtype)

    s_prev, dp_prev = start(0)
    for h in range(1, num_heads):
        s_next, dp_next = start(h)
        finish(h - 1, s_prev, dp_prev)
        s_prev, dp_prev = s_next, dp_next
    finish(num_heads - 1, s_prev, dp_prev)


def _fwd_bhsd(q, k, v, *, causal):
    """q,k,v: [B, H, S, D] → out [B, H, S, D]."""
    b, h, s, d = q.shape
    spec = pl.BlockSpec((1, h, s, d), lambda i: (i, 0, 0, 0))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, num_heads=h),
        grid=(b,),
        in_specs=[spec, spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        interpret=_interpret(),
    )(q, k, v)


def _bwd_bhsd(q, k, v, do, o, *, causal):
    b, h, s, d = q.shape
    spec = pl.BlockSpec((1, h, s, d), lambda i: (i, 0, 0, 0))
    return pl.pallas_call(
        functools.partial(_bwd_kernel, causal=causal, num_heads=h),
        grid=(b,),
        in_specs=[spec, spec, spec, spec, spec],
        out_specs=[spec, spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, do, o)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused(q, k, v, causal):
    out, _ = _fused_fwd(q, k, v, causal)
    return out


def _fused_fwd(q, k, v, causal):
    """q,k,v: [B, S, H, D], q pre-scaled, equal head counts (GQA by caller)."""
    q_t = q.transpose(0, 2, 1, 3)                  # [B, H, S, D]
    k_t = k.transpose(0, 2, 1, 3)
    v_t = v.transpose(0, 2, 1, 3)
    out = _fwd_bhsd(q_t, k_t, v_t, causal=causal)
    # the [B, H, S, D] output is a residual: the backward's delta term
    # needs only rowsum(do * o), not the [S, S] probability tile
    return out.transpose(0, 2, 1, 3), (q_t, k_t, v_t, out)


def _fused_bwd(causal, residuals, g):
    q_t, k_t, v_t, o_t = residuals
    do = g.transpose(0, 2, 1, 3)
    dq, dk, dv = _bwd_bhsd(q_t, k_t, v_t, do, o_t, causal=causal)
    return (
        dq.transpose(0, 2, 1, 3),
        dk.transpose(0, 2, 1, 3),
        dv.transpose(0, 2, 1, 3),
    )


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Fused short-sequence attention over [B,S,H,D] tensors (differentiable).

    GQA-aware: kv heads are repeated to query heads *outside* the
    custom-vjp kernel, so the repeat's own VJP group-sums dk/dv
    automatically. Sequences longer than :data:`MAX_FUSED_SEQ` should use
    :func:`unionml_tpu.ops.flash_attention.flash_attention` instead.
    """
    if q.shape[1] > MAX_FUSED_SEQ:
        raise ValueError(
            f"fused_attention is for short sequences (<= {MAX_FUSED_SEQ}); "
            f"got {q.shape[1]} — use flash_attention"
        )
    if k.shape[1] != q.shape[1]:
        # the kernel's k/v blocks are shaped from q: unequal lengths would
        # silently read only the first q_len keys
        raise ValueError(
            f"fused_attention requires q_len == kv_len (got {q.shape[1]} vs "
            f"{k.shape[1]}) — use flash_attention or the xla reference"
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    num_heads = q.shape[2]
    if k.shape[2] != num_heads:
        from unionml_tpu.ops.attention import _repeat_kv

        k = _repeat_kv(k, num_heads)
        v = _repeat_kv(v, num_heads)
    # scale (and the exp2 log2(e) base change) rides the [B, S, H, D] q
    # (fused into the projection by XLA) rather than the [S, S] score tile
    # inside the kernel; the VJP factor on dq is handled by autodiff here,
    # outside the custom_vjp
    return _fused(q * jnp.asarray(scale * LOG2E, q.dtype), k, v, causal)
