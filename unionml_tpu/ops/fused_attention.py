"""Pallas TPU fused multi-head attention for short sequences.

At ViT/BERT sequence lengths (a few hundred tokens) attention is
*overhead*-bound, not memory-bound: a flash-style kernel with one program
per (batch, head) pays the fixed per-program pipeline cost 768 times for
microseconds of MXU work each (measured on v5e: ~1.2 us/program floor —
more than the matmuls themselves). This kernel instead runs ONE program
per batch element — grid ``(B,)`` — and loops over heads inside the
program, with the full ``S x S`` fp32 score tile resident in VMEM (200 KB
at S=224; use :mod:`unionml_tpu.ops.flash_attention` beyond ~1k tokens
where the tile stops fitting).

The backward is a single program per batch element too: with the whole
sequence in VMEM there is no cross-program accumulation, so softmax is
simply recomputed per head (no logsumexp residual) and dq/dk/dv are
written in one pass — five small matmuls per head, all fp32-accumulated
on the MXU via ``preferred_element_type``.

Layout: tensors are transposed to ``[B, H, S, D]`` outside the kernel so
each head slice ``ref[0, h]`` is a contiguous ``[S, D]`` tile (slicing a
leading block dim is free; slicing lanes is not).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from unionml_tpu.ops.flash_attention import NEG_INF, _interpret

# Above this sequence length the S x S fp32 score tile (plus operands)
# stops fitting comfortably in VMEM; callers should use flash_attention.
MAX_FUSED_SEQ = 1024


def _causal_mask(s_len):
    q_pos = jax.lax.broadcasted_iota(jnp.int32, (s_len, s_len), 0)
    kv_pos = jax.lax.broadcasted_iota(jnp.int32, (s_len, s_len), 1)
    return q_pos >= kv_pos


def _softmax_fp32(s):
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    return p / jnp.sum(p, axis=-1, keepdims=True)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, num_heads):
    for h in range(num_heads):
        q = q_ref[0, h]                            # [S, D] input dtype
        k = k_ref[0, h]
        v = v_ref[0, h]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                  # [S, S] fp32
        if causal:
            s = jnp.where(_causal_mask(s.shape[0]), s, NEG_INF)
        p = _softmax_fp32(s)
        o_ref[0, h] = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(o_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, dq_ref, dk_ref, dv_ref, *,
                scale, causal, num_heads):
    for h in range(num_heads):
        q = q_ref[0, h]
        k = k_ref[0, h]
        v = v_ref[0, h]
        do = do_ref[0, h]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            s = jnp.where(_causal_mask(s.shape[0]), s, NEG_INF)
        p = _softmax_fp32(s)                       # [S, S] fp32
        p_cast = p.astype(do.dtype)
        dv_ref[0, h] = jax.lax.dot_general(
            p_cast, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                          # [S, S]
        delta = jnp.sum(p * dp, axis=-1, keepdims=True)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dq_ref[0, h] = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ).astype(dq_ref.dtype)
        dk_ref[0, h] = jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        ).astype(dk_ref.dtype)


def _fwd_bhsd(q, k, v, *, causal, scale):
    """q,k,v: [B, H, S, D] → out [B, H, S, D]."""
    b, h, s, d = q.shape
    spec = pl.BlockSpec((1, h, s, d), lambda i: (i, 0, 0, 0))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, num_heads=h),
        grid=(b,),
        in_specs=[spec, spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        interpret=_interpret(),
    )(q, k, v)


def _bwd_bhsd(q, k, v, do, *, causal, scale):
    b, h, s, d = q.shape
    spec = pl.BlockSpec((1, h, s, d), lambda i: (i, 0, 0, 0))
    return pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale, causal=causal, num_heads=h),
        grid=(b,),
        in_specs=[spec, spec, spec, spec],
        out_specs=[spec, spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, do)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused(q, k, v, causal, scale):
    out, _ = _fused_fwd(q, k, v, causal, scale)
    return out


def _fused_fwd(q, k, v, causal, scale):
    """q,k,v: [B, S, H, D] with equal head counts (GQA handled by caller)."""
    q_t = q.transpose(0, 2, 1, 3)                  # [B, H, S, D]
    k_t = k.transpose(0, 2, 1, 3)
    v_t = v.transpose(0, 2, 1, 3)
    out = _fwd_bhsd(q_t, k_t, v_t, causal=causal, scale=scale)
    return out.transpose(0, 2, 1, 3), (q_t, k_t, v_t)


def _fused_bwd(causal, scale, residuals, g):
    q_t, k_t, v_t = residuals
    do = g.transpose(0, 2, 1, 3)
    dq, dk, dv = _bwd_bhsd(q_t, k_t, v_t, do, causal=causal, scale=scale)
    return (
        dq.transpose(0, 2, 1, 3),
        dk.transpose(0, 2, 1, 3),
        dv.transpose(0, 2, 1, 3),
    )


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Fused short-sequence attention over [B,S,H,D] tensors (differentiable).

    GQA-aware: kv heads are repeated to query heads *outside* the
    custom-vjp kernel, so the repeat's own VJP group-sums dk/dv
    automatically. Sequences longer than :data:`MAX_FUSED_SEQ` should use
    :func:`unionml_tpu.ops.flash_attention.flash_attention` instead.
    """
    if q.shape[1] > MAX_FUSED_SEQ:
        raise ValueError(
            f"fused_attention is for short sequences (<= {MAX_FUSED_SEQ}); "
            f"got {q.shape[1]} — use flash_attention"
        )
    if k.shape[1] != q.shape[1]:
        # the kernel's k/v blocks are shaped from q: unequal lengths would
        # silently read only the first q_len keys
        raise ValueError(
            f"fused_attention requires q_len == kv_len (got {q.shape[1]} vs "
            f"{k.shape[1]}) — use flash_attention or the xla reference"
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    num_heads = q.shape[2]
    if k.shape[2] != num_heads:
        from unionml_tpu.ops.attention import _repeat_kv

        k = _repeat_kv(k, num_heads)
        v = _repeat_kv(v, num_heads)
    return _fused(q, k, v, causal, scale)
