"""Push exporters: dependency-free OTLP/HTTP JSON for spans + metrics.

The telemetry layer (:mod:`unionml_tpu.telemetry`) is pull-only: spans
sit in the recorder ring until someone exports them, metrics exist for
whoever scrapes ``GET /metrics``. In a fleet, that is not enough — the
collector is a remote service and the serving process must *push*.
This module is the push half, speaking the OTLP/HTTP **JSON** encoding
(`opentelemetry-proto` JSON mapping, ``/v1/traces`` and
``/v1/metrics``) with nothing beyond the stdlib:

- :class:`OtlpExporter` — subscribes to a
  :class:`~unionml_tpu.telemetry.TraceRecorder` (every finished request
  is enqueued as a connected span tree: synthesized root span + child
  spans, W3C trace/span/parent ids intact) and periodically snapshots a
  :class:`~unionml_tpu.telemetry.MetricsRegistry` into OTLP gauge /
  sum / histogram points. A **bounded** queue absorbs bursts (overflow
  increments ``unionml_otlp_spans_dropped_total`` — never blocks the
  serving path); a background thread batches, POSTs, and retries with
  exponential backoff + deterministic jitter; a batch that exhausts its
  retries is dropped and counted
  (``unionml_otlp_export_failures_total{signal}``) rather than wedging
  the queue. Resource attributes carry the host, backend, and build
  info so a collector can tell replicas apart.
- :class:`OtlpCollectorStub` — a stdlib-HTTP-server collector double
  for tests and benches: records every decoded payload, and can be
  armed to fail the next N posts so retry/drop behavior is testable
  without a network.

Configuration: ``ServingApp(otlp_endpoint="http://collector:4318")``
or ``UNIONML_TPU_OTLP_ENDPOINT`` (the standard OTLP/HTTP port; the
exporter appends ``/v1/traces`` / ``/v1/metrics``). Everything here is
stdlib-only and safe to import before jax.
"""

from __future__ import annotations

import json
import random
import socket
import sys
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from unionml_tpu import telemetry
from unionml_tpu._logging import logger

__all__ = [
    "OtlpCollectorStub",
    "OtlpExporter",
    "default_resource",
    "encode_metrics",
    "encode_spans",
]


def _attr_value(value: Any) -> Dict[str, Any]:
    """One OTLP AnyValue (the JSON mapping's tagged-union encoding)."""
    if isinstance(value, bool):
        return {"boolValue": value}
    if isinstance(value, int):
        return {"intValue": str(value)}  # int64 is a JSON string in OTLP
    if isinstance(value, float):
        return {"doubleValue": value}
    if isinstance(value, (list, tuple)):
        return {"arrayValue": {"values": [_attr_value(v) for v in value]}}
    return {"stringValue": str(value)}


def _attrs(mapping: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [
        {"key": str(k), "value": _attr_value(v)} for k, v in mapping.items()
    ]


def default_resource(service_name: str = "unionml-tpu") -> Dict[str, Any]:
    """The exporter's resource attributes: service/host identity plus
    the same build/runtime info ``unionml_tpu_build_info`` publishes
    (jax stays unimported — ``backend="unloaded"`` until something else
    loads it, exactly like :func:`telemetry.publish_process_metrics`)."""
    try:
        from unionml_tpu import __version__ as version
    except Exception:
        version = "unknown"
    jax_version, backend = "unloaded", "unloaded"
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        jax_version = str(getattr(jax_mod, "__version__", "unknown"))
        try:
            backend = str(jax_mod.default_backend())
        except Exception:
            backend = "unknown"
    return {
        "service.name": service_name,
        "service.version": str(version),
        "host.name": socket.gethostname(),
        "telemetry.sdk.name": "unionml-tpu",
        "telemetry.sdk.language": "python",
        "unionml_tpu.jax_version": jax_version,
        "unionml_tpu.backend": backend,
    }


def _ns(perf_s: float, wall_offset_s: float) -> str:
    """perf_counter seconds → epoch nanoseconds (OTLP wants a uint64
    JSON string). ``wall_offset_s`` anchors the monotonic clock to the
    wall clock once, at exporter construction."""
    return str(max(0, int((perf_s + wall_offset_s) * 1e9)))


def encode_spans(
    requests: List[Tuple[str, dict, List[dict]]],
    resource: Dict[str, Any],
    wall_offset_s: float,
) -> dict:
    """Finished recorder requests → one OTLP/HTTP JSON
    ``ExportTraceServiceRequest``.

    Each request becomes a **connected tree**: a synthesized root span
    (named by the request kind, covering the request's start→finish,
    parented to the inbound context when one was propagated) plus one
    child span per recorded span. Ids are the recorder's real W3C ids,
    so a collector stitches this tree under the caller's."""
    otlp_spans: List[dict] = []
    for rid, meta, spans in requests:
        trace_id = meta.get("trace_id") or telemetry.new_trace_id()
        root_id = meta.get("span_id") or telemetry.new_span_id()
        start_s = meta.get("start_s")
        end_s = meta.get("end_s")
        if spans:
            start_s = min([s["start_s"] for s in spans] + (
                [start_s] if start_s is not None else []
            ))
            end_s = max([s["end_s"] for s in spans] + (
                [end_s] if end_s is not None else []
            ))
        if start_s is None or end_s is None:
            continue  # nothing measurable to ship
        root_attrs = {"unionml.request_id": rid}
        if meta.get("truncated"):
            root_attrs["unionml.truncated"] = True
        for key, value in meta.items():
            if key not in (
                "kind", "trace_id", "span_id", "parent_span_id",
                "sampled", "start_s", "end_s", "truncated", "events",
            ):
                root_attrs[f"unionml.{key}"] = value
        root: Dict[str, Any] = {
            "traceId": trace_id,
            "spanId": root_id,
            "name": str(meta.get("kind", "request")),
            "kind": 2,  # SPAN_KIND_SERVER
            "startTimeUnixNano": _ns(start_s, wall_offset_s),
            "endTimeUnixNano": _ns(end_s, wall_offset_s),
            "attributes": _attrs(root_attrs),
        }
        if meta.get("parent_span_id"):
            root["parentSpanId"] = meta["parent_span_id"]
        instants = meta.get("events")
        if instants:
            # recorder instants → OTLP span events on the root span
            # (the fleet timeline's eject/probe/rejoin/scale_* marks)
            root["events"] = [
                {
                    "timeUnixNano": _ns(ev["t_s"], wall_offset_s),
                    "name": str(ev["name"]),
                    **(
                        {"attributes": _attrs({
                            str(k): v for k, v in ev["args"].items()
                        })}
                        if ev.get("args") else {}
                    ),
                }
                for ev in instants
            ]
        otlp_spans.append(root)
        for span in spans:
            child: Dict[str, Any] = {
                "traceId": trace_id,
                "spanId": span.get("span_id") or telemetry.new_span_id(),
                # an explicit per-span parent (the router nests hedge
                # lanes / attempts this way) wins over the root default
                "parentSpanId": span.get("parent_span_id") or root_id,
                "name": str(span["name"]),
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": _ns(span["start_s"], wall_offset_s),
                "endTimeUnixNano": _ns(span["end_s"], wall_offset_s),
            }
            args = span.get("args")
            if args:
                child["attributes"] = _attrs({
                    str(k): v for k, v in args.items()
                })
            otlp_spans.append(child)
    return {
        "resourceSpans": [{
            "resource": {"attributes": _attrs(resource)},
            "scopeSpans": [{
                "scope": {"name": "unionml_tpu.telemetry"},
                "spans": otlp_spans,
            }],
        }]
    }


def encode_metrics(
    registry: "telemetry.MetricsRegistry",
    resource: Dict[str, Any],
    now_unix_ns: int,
) -> dict:
    """Registry snapshot → one OTLP/HTTP JSON
    ``ExportMetricsServiceRequest``: counters as cumulative monotonic
    sums, gauges as gauges, histograms as cumulative explicit-bounds
    histograms (the exact same numbers ``GET /metrics`` exposes)."""
    now = str(int(now_unix_ns))
    metrics: List[dict] = []
    for family in sorted(registry.collect(), key=lambda f: f.name):
        points: List[dict] = []
        if family.kind == "histogram":
            for values, child in sorted(family.children()):
                buckets = child.buckets()  # cumulative (bound, count)
                counts, prev = [], 0
                for _, cum in buckets:
                    counts.append(str(cum - prev))
                    prev = cum
                points.append({
                    "attributes": _attrs(
                        dict(zip(family.labelnames, values))
                    ),
                    "timeUnixNano": now,
                    "count": str(child.count),
                    "sum": child.sum,
                    "bucketCounts": counts,
                    "explicitBounds": [b for b, _ in buckets[:-1]],
                })
            metric = {
                "name": family.name,
                "description": family.help,
                "histogram": {
                    "aggregationTemporality": 2,  # CUMULATIVE
                    "dataPoints": points,
                },
            }
        else:
            for values, child in sorted(family.children()):
                points.append({
                    "attributes": _attrs(
                        dict(zip(family.labelnames, values))
                    ),
                    "timeUnixNano": now,
                    "asDouble": float(child.value),
                })
            if family.kind == "counter":
                metric = {
                    "name": family.name,
                    "description": family.help,
                    "sum": {
                        "aggregationTemporality": 2,
                        "isMonotonic": True,
                        "dataPoints": points,
                    },
                }
            else:
                metric = {
                    "name": family.name,
                    "description": family.help,
                    "gauge": {"dataPoints": points},
                }
        metrics.append(metric)
    return {
        "resourceMetrics": [{
            "resource": {"attributes": _attrs(resource)},
            "scopeMetrics": [{
                "scope": {"name": "unionml_tpu.telemetry"},
                "metrics": metrics,
            }],
        }]
    }


class OtlpExporter:
    """Background OTLP/HTTP JSON exporter for spans and metric
    snapshots.

    Subscribes to ``tracer`` finished-request events into a bounded
    queue (``max_queue`` requests; overflow drops the OLDEST and
    counts ``unionml_otlp_spans_dropped_total`` — the serving path
    never blocks on export), and every ``interval_s`` the worker
    drains up to ``max_batch`` requests to ``<endpoint>/v1/traces``
    and ships one registry snapshot to ``<endpoint>/v1/metrics``.

    Each POST retries up to ``max_retries`` times on transport errors
    and 5xx/429, sleeping ``backoff_s * 2**attempt`` plus deterministic
    jitter (seeded PRNG — reproducible in tests, desynchronized across
    replicas via the host/pid-derived default seed), capped at
    ``backoff_cap_s``. A batch that exhausts retries is dropped and
    counted in ``unionml_otlp_export_failures_total{signal}`` —
    a dead collector costs bounded memory and zero request latency.

    Use :meth:`flush` in tests/benches for a synchronous drain;
    :meth:`close` unsubscribes, flushes once, and stops the worker.
    """

    def __init__(
        self,
        endpoint: str,
        *,
        registry: Optional["telemetry.MetricsRegistry"] = None,
        tracer: Optional["telemetry.TraceRecorder"] = None,
        service_name: str = "unionml-tpu",
        interval_s: float = 5.0,
        max_queue: int = 2048,
        max_batch: int = 256,
        timeout_s: float = 5.0,
        max_retries: int = 3,
        backoff_s: float = 0.25,
        backoff_cap_s: float = 5.0,
        headers: Optional[Dict[str, str]] = None,
        resource_attributes: Optional[Dict[str, Any]] = None,
        export_metrics: bool = True,
        seed: Optional[int] = None,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.interval_s = float(interval_s)
        self.max_queue = int(max_queue)
        self.max_batch = int(max_batch)
        self.timeout_s = float(timeout_s)
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.export_metrics = bool(export_metrics)
        self._headers = {"Content-Type": "application/json", **(headers or {})}
        self._registry = (
            registry if registry is not None else telemetry.get_registry()
        )
        self._tracer = tracer if tracer is not None else telemetry.get_tracer()
        self.resource = {
            **default_resource(service_name),
            **(resource_attributes or {}),
        }
        # monotonic→wall anchor for span timestamps (lint: wall clock is
        # fine here — this is an epoch timestamp, not a duration)
        self._wall_offset_s = time.time() - time.perf_counter()
        self._rng = random.Random(
            seed if seed is not None else hash((socket.gethostname(), id(self)))
        )
        self._lock = threading.Lock()
        self._queue: "deque[Tuple[str, dict, List[dict]]]" = deque()
        R = self._registry
        self._m_dropped = R.counter(
            "unionml_otlp_spans_dropped_total",
            "Finished requests dropped because the OTLP export queue "
            "was full.",
        )
        self._m_exported = R.counter(
            "unionml_otlp_exported_spans_total",
            "Spans successfully delivered to the OTLP endpoint.",
        )
        self._m_retries = R.counter(
            "unionml_otlp_export_retries_total",
            "OTLP POST attempts retried after a transport error or "
            "retryable status.",
        )
        failures = R.counter(
            "unionml_otlp_export_failures_total",
            "OTLP batches dropped after exhausting retries, by signal.",
            ("signal",),
        )
        self._m_failures = {
            signal: failures.labels(signal) for signal in ("traces", "metrics")
        }
        self._tracer.add_listener(self._on_finish)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._worker = threading.Thread(
            target=self._run, daemon=True, name="unionml-tpu-otlp-export"
        )
        self._worker.start()

    # -- ingestion (tracer listener: runs on the finishing thread) -------

    def _on_finish(self, rid: str, meta: dict, spans: List[dict]) -> None:
        dropped = 0
        with self._lock:
            self._queue.append((rid, meta, spans))
            while len(self._queue) > self.max_queue:
                self._queue.popleft()
                dropped += 1
        if dropped:
            self._m_dropped.inc(dropped)

    # -- transport --------------------------------------------------------

    def _post(self, path: str, payload: dict, signal: str) -> bool:
        body = json.dumps(payload).encode()
        for attempt in range(self.max_retries + 1):
            try:
                req = urllib.request.Request(
                    f"{self.endpoint}{path}", data=body,
                    headers=self._headers, method="POST",
                )
                with urllib.request.urlopen(req, timeout=self.timeout_s):
                    return True
            except urllib.error.HTTPError as exc:
                # 4xx (except 429) means the payload itself is refused:
                # retrying the same bytes cannot succeed
                retryable = exc.code == 429 or exc.code >= 500
                if not retryable:
                    logger.info(
                        f"otlp export refused ({signal}): HTTP {exc.code}"
                    )
                    break
            except (urllib.error.URLError, OSError, TimeoutError):
                pass  # transport error: retry
            if attempt >= self.max_retries:
                break
            self._m_retries.inc()
            delay = min(
                self.backoff_cap_s, self.backoff_s * (2.0 ** attempt)
            ) * (1.0 + 0.5 * self._rng.random())
            if self._stop.wait(delay):  # close() aborts the backoff
                break
        self._m_failures[signal].inc()
        return False

    # -- worker -----------------------------------------------------------

    def _flush_once(self) -> None:
        with self._lock:
            batch = [
                self._queue.popleft()
                for _ in range(min(self.max_batch, len(self._queue)))
            ]
        if batch:
            payload = encode_spans(batch, self.resource, self._wall_offset_s)
            n_spans = len(payload["resourceSpans"][0]["scopeSpans"][0]["spans"])
            if self._post("/v1/traces", payload, "traces"):
                self._m_exported.inc(n_spans)
        if self.export_metrics:
            now_ns = int(time.time() * 1e9)
            self._post(
                "/v1/metrics",
                encode_metrics(self._registry, self.resource, now_ns),
                "metrics",
            )

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                break
            try:
                self._flush_once()
            except Exception as exc:  # the exporter must never crash
                logger.info(f"otlp export error: {exc!r}")

    def flush(self) -> None:
        """Synchronously export everything queued right now (tests and
        benches; production relies on the interval worker)."""
        self._flush_once()

    def pending(self) -> int:
        with self._lock:
            return len(self._queue)

    def close(self, flush: bool = True) -> None:
        """Unsubscribe from the tracer, stop the worker, and optionally
        attempt one final flush. ``_stop`` is set BEFORE the flush so
        its backoff sleeps short-circuit: shutdown against a dead
        collector costs at most one POST timeout per signal, not the
        full retry ladder — a rolling restart must not hang on its
        telemetry."""
        self._tracer.remove_listener(self._on_finish)
        self._stop.set()
        self._wake.set()
        self._worker.join(timeout=5.0)
        if flush:
            try:
                self._flush_once()
            except Exception as exc:
                logger.info(f"otlp final flush failed: {exc!r}")


class OtlpCollectorStub:
    """In-process OTLP/HTTP collector double (tests + benches).

    Accepts POSTs on any path, decodes the JSON body, and appends
    ``(path, payload)`` to :attr:`requests`. ``fail(n)`` arms the next
    ``n`` posts to answer ``status`` instead (retry/backoff tests);
    counts land in :attr:`failures_served`.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.requests: List[Tuple[str, dict]] = []
        self.failures_served = 0
        self._fail_next = 0
        self._fail_status = 503
        self._lock = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                with stub._lock:
                    if stub._fail_next > 0:
                        stub._fail_next -= 1
                        stub.failures_served += 1
                        status = stub._fail_status
                    else:
                        try:
                            stub.requests.append(
                                (self.path, json.loads(raw or b"{}"))
                            )
                            status = 200
                        except json.JSONDecodeError:
                            status = 400
                body = b"{}"
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="unionml-tpu-otlp-collector-stub",
        )
        self._thread.start()

    @property
    def endpoint(self) -> str:
        host, port = self._server.server_address
        return f"http://{host}:{port}"

    def fail(self, n: int, status: int = 503) -> None:
        """Answer ``status`` for the next ``n`` posts (then recover)."""
        with self._lock:
            self._fail_next = int(n)
            self._fail_status = int(status)

    def payloads(self, path: str) -> List[dict]:
        """Decoded payloads posted to ``path`` (e.g. ``/v1/traces``)."""
        with self._lock:
            return [p for seen_path, p in self.requests if seen_path == path]

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
