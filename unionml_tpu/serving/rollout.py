"""Zero-downtime model lifecycle: versioned rollouts with canary
pools, shadow traffic, and SLO-guarded auto-rollback.

Two pieces (docs/robustness.md "Rollouts & rollback"):

- :class:`VersionRegistry` — a versioned weight store backed by the
  checkpoint manager (:func:`~unionml_tpu.checkpoint.make_checkpoint_
  manager`). A *version* is a committed checkpoint plus metadata: the
  commit-marker protocol means a torn or in-progress publish is simply
  not a version (refused exactly as ``restore`` refuses it), so the
  registry can never hand a rollout half-written weights.
- :class:`RolloutController` — choreographs a release end-to-end
  through the router's existing actuators. It owns no dispatch path of
  its own: canaries are provisioned through the same
  :class:`~unionml_tpu.serving.autoscaler.ReplicaProvisioner` +
  warm-join donor machinery the autoscaler uses (canaries join
  cache-warm), traffic splits through the router's version-aware pick
  (percentage, per-tenant, or a hard ``X-Model-Version`` request pin),
  promotion is the existing drain → ``bind()`` → rejoin rolling
  restart, and abort/rollback drains ONLY canaries — live capacity is
  never touched by a failed rollout.

Shadow traffic: while a canary bakes, live requests are duplicated
onto it (dispatched directly on the canary handle from a dedicated
worker thread — never through the router envelope, so a shadow can
never consume the live retry budget, count toward live SLO burn, or
bill a live tenant). The engine decodes deterministically, so the
shadow's tokens are diffed **exactly** against the live answer: any
divergence is a real model-behavior delta, not sampling noise. A
wedged or dead canary degrades shadowing to *off* (flight
``rollout_hold{shadow_degraded}``) — never an error on the live path.

Control discipline is copied from the
:class:`~unionml_tpu.serving.autoscaler.FleetAutoscaler`: one decision
per :meth:`~RolloutController.evaluate` tick, an injectable monotonic
clock (never wall time — an NTP step must not corrupt a bake window),
a CLOSED reason vocabulary (:data:`ROLLOUT_REASONS`, lint-enforced
against the docs), and hysteresis so one bad request cannot flap a
rollout. Every transition is reconstructible post-hoc from
``unionml_rollout_decisions_total{decision,reason}``, the flight ring,
the fleet timeline, and ``GET /debug/rollout``.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from unionml_tpu import telemetry
from unionml_tpu.checkpoint.async_writer import is_committed
from unionml_tpu.checkpoint import make_checkpoint_manager
from unionml_tpu.serving.autoscaler import ReplicaProvisioner
from unionml_tpu.serving.scheduler import (
    DEFAULT_MODEL_VERSION,
    current_model_version,
    model_version_scope,
    priority_scope,
    validate_model_version,
)
from unionml_tpu.serving.usage import tenant_scope

logger = logging.getLogger("unionml_tpu.serving")

# the tenant shadow dispatches bill to: live tenants must never pay
# for duplicate traffic, but the canary's ledger should still show
# where its load came from
SHADOW_TENANT = "rollout-shadow"

ROLLOUT_DECISIONS = ("rollout_advance", "rollout_hold", "rollout_rollback")

# CLOSED decision-reason vocabulary (docs/robustness.md "Rollout
# decision table"; scripts/lint_basics.py enforces the doc two-way).
# Every evaluate() tick and every operator call lands in exactly one
# (decision, reason) child of unionml_rollout_decisions_total, so the
# whole lifecycle is reconstructible from counters + flight events.
ROLLOUT_REASONS = (
    "operator",           # start()/promote()/abort() operator call
    "canary_join",        # one canary provisioned, warmed, and joined
    "canary_ready",       # canary pool complete → split + shadow open
    "baking",             # observation window running (steady hold)
    "hysteresis",         # bad signal, below the sustain streak
    "bake_complete",      # clean bake window → promotion begins
    "promote_replica",    # one live replica drained, rebound, rejoined
    "drain_timeout",      # a promote target would not drain/bind; held
    "reap_canary",        # one canary drained and released post-promote
    "complete",           # fleet live on the new version; rollout done
    "slo_burn",           # canary SLO burn over threshold → rollback
    "parity_regression",  # shadow divergence over tolerance → rollback
    "canary_dead",        # canary unreachable/ejected too long → rollback
    "shadow_degraded",    # shadowing switched off (wedged/dead canary)
    "provision_failed",   # canary provision/join raised; backoff set
    "provision_backoff",  # provisioning waits out the failure backoff
    "idle",               # no rollout in progress (steady hold)
)

# steady holds stay out of the flight ring and off the fleet timeline
# (a 1 s ticker would flush real request events in minutes); they still
# count in the decisions metric so the tick cadence is observable
_STEADY_REASONS = ("idle", "baking")

_SHADOW_RESULTS = ("match", "diverged", "error", "dropped")

_VERSION_META = "version.json"


class VersionRegistry:
    """Committed checkpoints + metadata as named model versions.

    Backed by a :func:`~unionml_tpu.checkpoint.make_checkpoint_manager`
    store: :meth:`publish` writes the weights through the manager's
    crash-safe commit protocol (tmp dir → fsync'd ``_COMMITTED`` marker
    → atomic rename) and only then drops a ``version.json`` metadata
    sidecar inside the committed dir. :meth:`versions` lists committed
    steps ONLY — a torn or uncommitted dir is invisible, refused
    exactly as :meth:`~unionml_tpu.checkpoint.async_writer
    .AsyncCheckpointManager.restore` refuses it — so a rollout can
    never pick up half-written weights.

    Version ids are validated by the same closed grammar as the
    ``X-Model-Version`` header (:func:`~unionml_tpu.serving.scheduler
    .validate_model_version`); ``auto`` is reserved (the no-pin
    sentinel). A committed checkpoint saved outside :meth:`publish`
    (plain training flow) is still listed, under the derived id
    ``v<step>``.
    """

    def __init__(
        self,
        root: Union[str, Path],
        *,
        manager=None,
        max_versions: Optional[int] = None,
        backend: str = "auto",
    ):
        self.root = Path(root).absolute()
        self._manager = manager if manager is not None else (
            make_checkpoint_manager(
                self.root, max_to_keep=max_versions, backend=backend,
                async_commit=False,
            )
        )

    # -- write side --------------------------------------------------------

    def publish(
        self, version: str, state: Any, *,
        metadata: Optional[dict] = None,
    ) -> str:
        """Durably store ``state`` as ``version``; returns the id.

        The save is synchronous through the manager's commit barrier:
        when :meth:`publish` returns, the version is either fully
        committed and listed, or it never happened — there is no
        observable in-between for a rollout to race."""
        version = validate_model_version(version)
        if version == DEFAULT_MODEL_VERSION:
            raise ValueError(
                f"version id {DEFAULT_MODEL_VERSION!r} is reserved as the "
                "no-pin sentinel — pick a real id"
            )
        if version in self.versions():
            raise ValueError(f"version {version!r} already published")
        steps = self._committed_steps()
        step = (max(steps) if steps else 0) + 1
        self._manager.save(step, state)
        self._manager.wait()
        # metadata sidecar AFTER the commit barrier: a crash between
        # save and this write leaves a committed checkpoint under the
        # derived id, never a version pointing at torn weights. The
        # sidecar itself lands atomically (tmp + rename).
        meta_path = self.root / f"step_{step}" / _VERSION_META
        tmp = meta_path.with_name(_VERSION_META + ".tmp")
        tmp.write_text(json.dumps({
            "version": version, "step": step,
            "metadata": dict(metadata or {}),
        }))
        tmp.replace(meta_path)
        return version

    # -- read side ---------------------------------------------------------

    def _committed_steps(self) -> List[int]:
        steps = []
        for p in self.root.glob("step_*"):
            try:
                step = int(p.name[len("step_"):])
            except ValueError:
                continue  # step_N.tmp-* in-progress dirs
            if is_committed(p):
                steps.append(step)
        return sorted(steps)

    def versions(self) -> Dict[str, dict]:
        """``{version_id: {"step", "metadata"}}`` for every committed
        version, oldest step first. Torn/uncommitted dirs never
        appear."""
        out: Dict[str, dict] = {}
        for step in self._committed_steps():
            meta_path = self.root / f"step_{step}" / _VERSION_META
            vid, metadata = f"v{step}", {}
            if meta_path.exists():
                try:
                    doc = json.loads(meta_path.read_text())
                    vid = validate_model_version(doc.get("version"))
                    metadata = dict(doc.get("metadata") or {})
                except (ValueError, KeyError, TypeError):
                    # a corrupt sidecar degrades to the derived id —
                    # the weights themselves are commit-protected
                    vid, metadata = f"v{step}", {}
            out[vid] = {"step": step, "metadata": metadata}
        return out

    def latest(self) -> Optional[str]:
        """Newest published version id, or ``None`` when empty."""
        vid = None
        for vid in self.versions():
            pass
        return vid

    def resolve(self, version: str) -> dict:
        """The ``{"step", "metadata"}`` record behind ``version``;
        ``ValueError`` (the 422 class) for an id that names no
        committed version."""
        version = validate_model_version(version)
        info = self.versions().get(version)
        if info is None:
            raise ValueError(
                f"unknown model version {version!r} — published: "
                f"{sorted(self.versions())}"
            )
        return info

    def load(self, version: str, state_target: Any) -> Any:
        """Restore ``version``'s weights into ``state_target``'s
        structure. Rides the manager's restore path, so a torn dir
        (crash after the sidecar scan) still refuses to load."""
        info = self.resolve(version)
        return self._manager.restore(state_target, step=info["step"])

    def close(self) -> None:
        self._manager.close()


class RolloutPolicy:
    """Tunable thresholds for one :class:`RolloutController`.

    canary_replicas: canary pool size the provisioning stage builds.
    canary_percent: share (0–100) of *unpinned* traffic the router's
        version-aware pick steers to the canary version while baking.
    shadow / shadow_sample / shadow_queue: duplicate live requests
        onto the canary (all of them at ``shadow_sample=1.0``; the
        bounded queue drops — and counts — shadows under burst, it
        never blocks the live path).
    canary_burn_threshold: canary health-dict SLO burn score at or
        above which an evaluation counts as *bad*.
    divergence_tolerance: shadow divergences tolerated per evaluation
        window before the window counts as bad (0 = any divergence).
    sustain_evals: consecutive bad evaluations before auto-rollback —
        the hysteresis that stops one bad request flapping a rollout.
    bake_evals: consecutive clean evaluations before auto-promotion
        (``auto_promote=False`` holds at baked until operator
        :meth:`~RolloutController.promote`).
    canary_dead_evals: consecutive evaluations with an unreachable/
        ejected canary before rollback (its own hysteresis: a breaker
        blip must not kill a rollout).
    shadow_degrade_failures: consecutive shadow dispatch failures
        before shadowing degrades to off.
    warm_blocks: hot prefix blocks imported into a joining canary from
        the warmest live donor (0 = join cold). NOTE: donor KV was
        computed under the LIVE weights — warm joins are only
        parity-safe when the new version preserves KV semantics
        (republish / serving-config change); set 0 for a real weight
        change or the imported blocks will show up as shadow
        divergences.
    drain_timeout_s: per-replica drain budget during promote/reap.
    provision_backoff_s / provision_backoff_max_s: exponential retry
        schedule after a canary provision failure.
    name_prefix: canary replica names are
        ``{prefix}-{version}-{i}`` — the version is IN the name so
        flight events stay attributable after the pool is reaped.
    """

    def __init__(
        self,
        *,
        canary_replicas: int = 1,
        canary_percent: float = 5.0,
        shadow: bool = True,
        shadow_sample: float = 1.0,
        shadow_queue: int = 16,
        canary_burn_threshold: float = 1.0,
        divergence_tolerance: int = 0,
        sustain_evals: int = 2,
        bake_evals: int = 3,
        auto_promote: bool = True,
        canary_dead_evals: int = 2,
        shadow_degrade_failures: int = 3,
        warm_blocks: int = 64,
        drain_timeout_s: float = 30.0,
        provision_backoff_s: float = 1.0,
        provision_backoff_max_s: float = 30.0,
        name_prefix: str = "canary",
    ):
        if canary_replicas < 1:
            raise ValueError(
                f"canary_replicas must be >= 1, got {canary_replicas}"
            )
        if not 0.0 <= canary_percent <= 100.0:
            raise ValueError(
                f"canary_percent must be in [0, 100], got {canary_percent}"
            )
        if not 0.0 <= shadow_sample <= 1.0:
            raise ValueError(
                f"shadow_sample must be in [0, 1], got {shadow_sample}"
            )
        if shadow_queue < 1:
            raise ValueError(f"shadow_queue must be >= 1, got {shadow_queue}")
        if divergence_tolerance < 0:
            raise ValueError(
                "divergence_tolerance must be >= 0, got "
                f"{divergence_tolerance}"
            )
        for knob, lo in (
            ("sustain_evals", sustain_evals),
            ("bake_evals", bake_evals),
            ("canary_dead_evals", canary_dead_evals),
            ("shadow_degrade_failures", shadow_degrade_failures),
        ):
            if lo < 1:
                raise ValueError(f"{knob} must be >= 1, got {lo}")
        if warm_blocks < 0:
            raise ValueError(f"warm_blocks must be >= 0, got {warm_blocks}")
        self.canary_replicas = int(canary_replicas)
        self.canary_percent = float(canary_percent)
        self.shadow = bool(shadow)
        self.shadow_sample = float(shadow_sample)
        self.shadow_queue = int(shadow_queue)
        self.canary_burn_threshold = float(canary_burn_threshold)
        self.divergence_tolerance = int(divergence_tolerance)
        self.sustain_evals = int(sustain_evals)
        self.bake_evals = int(bake_evals)
        self.auto_promote = bool(auto_promote)
        self.canary_dead_evals = int(canary_dead_evals)
        self.shadow_degrade_failures = int(shadow_degrade_failures)
        self.warm_blocks = int(warm_blocks)
        self.drain_timeout_s = float(drain_timeout_s)
        self.provision_backoff_s = float(provision_backoff_s)
        self.provision_backoff_max_s = float(provision_backoff_max_s)
        self.name_prefix = str(name_prefix)


class RolloutController:
    """One release at a time, one decision per tick.

    Stages: ``idle`` → :meth:`start` → ``provisioning`` (one canary
    provisioned + warm-joined per tick) → ``baking`` (traffic split +
    shadow diffing, burn/parity watched under hysteresis) → ``promoting``
    (one live replica per tick: drain → ``bind()`` → rejoin; then
    canaries reaped one per tick) → ``idle``. :meth:`abort` — or an
    auto-rollback on SLO burn / parity regression / dead canary —
    drains ONLY canaries (and, mid-promote, restores already-promoted
    replicas to the old weights); live capacity is never collateral.

    Mirrors the autoscaler's control discipline: ``evaluate(now=...)``
    with an injectable monotonic clock for deterministic tests,
    :meth:`start`/:meth:`stop` for a wall-thread ticker in production,
    and every decision recorded to
    ``unionml_rollout_decisions_total{decision,reason}`` + the flight
    ring + the router's fleet timeline.
    """

    def __init__(
        self,
        router,
        provisioner: ReplicaProvisioner,
        versions: VersionRegistry,
        *,
        policy: Optional[RolloutPolicy] = None,
        params_loader: Optional[Callable[[str], Any]] = None,
        state_target: Any = None,
        registry: Optional[telemetry.MetricsRegistry] = None,
        flight: Optional[telemetry.FlightRecorder] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.router = router
        self.provisioner = provisioner
        self.versions = versions
        self.policy = policy if policy is not None else RolloutPolicy()
        self._loader = params_loader
        self._state_target = state_target
        self._clock = clock
        self._registry = (
            registry if registry is not None else telemetry.get_registry()
        )
        self._flight = (
            flight if flight is not None else telemetry.get_flight_recorder()
        )
        self._eval_lock = threading.RLock()
        self._stage = "idle"
        self._version: Optional[str] = None
        self._params: Any = None
        self._canaries: Dict[str, Any] = {}
        # promoted live replicas keep their OLD weights on file so an
        # abort mid-promote can walk the fleet back, not just forward
        self._promoted: Dict[str, dict] = {}
        self._next_id = 0
        self._provision_failures = 0
        self._provision_retry_at = float("-inf")
        self._bad_streak = 0
        self._clean_evals = 0
        self._dead_streak = 0
        self._last_decision: Optional[dict] = None
        self._history: deque = deque(maxlen=128)
        self._ticker: Optional[threading.Thread] = None
        self._ticker_stop = threading.Event()
        # shadow lane state (worker thread + bounded queue)
        self._shadow_on = False
        self._shadow_degraded = False
        self._shadow_degrade_pending = False
        self._shadow_lock = threading.Lock()
        self._shadow_q: deque = deque()
        self._shadow_wake = threading.Event()
        self._shadow_stop = threading.Event()
        self._shadow_worker: Optional[threading.Thread] = None
        self._shadow_rr = 0
        self._sample_n = 0
        self._shadow_failures = 0
        self._shadow_stats = {r: 0 for r in _SHADOW_RESULTS}
        self._diverged_acked = 0
        # GET /debug/rollout and fleet_report read the controller
        # through this link (the autoscaler registration pattern)
        router.rollout = self
        R = self._registry
        self._m_decisions = R.counter(
            "unionml_rollout_decisions_total",
            "Rollout decisions by kind and (closed-set) reason — every "
            "evaluation and operator call lands in exactly one child, "
            "so a release is reconstructible from counters alone.",
            ("decision", "reason"),
        )
        self._m_shadow = R.counter(
            "unionml_rollout_shadow_requests_total",
            "Shadow dispatches onto the canary by outcome (match / "
            "diverged / error / dropped) — deterministic decode makes "
            "'diverged' a real model-behavior delta, not noise.",
            ("result",),
        )
        self._g_canaries = R.gauge(
            "unionml_rollout_canary_replicas",
            "Canary replicas currently joined to the router for the "
            "in-flight rollout (0 when idle — a nonzero value after "
            "rollback means a reap failed).",
        )

    # -- operator API ------------------------------------------------------

    def start_rollout(
        self,
        version: str,
        *,
        percent: Optional[float] = None,
        pin_tenants: Optional[Dict[str, str]] = None,
    ) -> dict:
        """Begin rolling ``version`` out. Fails fast — the version is
        resolved and its weights LOADED before any fleet mutation, so
        a torn checkpoint or unknown id can never strand a half-built
        canary pool."""
        with self._eval_lock:
            if self._stage != "idle":
                raise ValueError(
                    f"a rollout of {self._version!r} is already "
                    f"{self._stage} — abort() it first"
                )
            version = validate_model_version(version)
            self.versions.resolve(version)   # unknown id → ValueError/422
            self._params = self._load_params(version)
            self._version = version
            self._stage = "provisioning"
            self._next_id = 0
            self._provision_failures = 0
            self._provision_retry_at = float("-inf")
            self._bad_streak = self._clean_evals = self._dead_streak = 0
            self._percent = (
                self.policy.canary_percent if percent is None
                else float(percent)
            )
            self._pin_tenants = {
                tenant: validate_model_version(v)
                for tenant, v in (pin_tenants or {}).items()
            }
            self._shadow_degraded = False
            self._shadow_failures = 0
            return self._record("rollout_advance", "operator", {
                "stage": "provisioning", "version": version,
            })

    def promote(self) -> dict:
        """Operator-forced promotion (skips the remaining bake)."""
        with self._eval_lock:
            if self._stage != "baking":
                raise ValueError(
                    f"nothing to promote: rollout stage is {self._stage!r}"
                )
            self._stage = "promoting"
            self._disable_shadow()
            return self._record("rollout_advance", "operator", {
                "stage": "promoting", "version": self._version,
            })

    def abort(self) -> dict:
        """Operator abort: drain canaries (and walk back any promoted
        replicas), never touch live capacity."""
        with self._eval_lock:
            if self._stage == "idle":
                raise ValueError("no rollout in progress")
            return self._rollback("operator", {"stage": self._stage})

    # -- weights -----------------------------------------------------------

    def _load_params(self, version: str):
        if self._loader is not None:
            return self._loader(version)
        if self._state_target is not None:
            return self.versions.load(version, self._state_target)
        raise ValueError(
            "RolloutController cannot load version weights: pass "
            "params_loader= (version id -> params) or state_target= "
            "(the restore structure) at construction"
        )

    @staticmethod
    def _bind_version(handle, params, version: str) -> None:
        """Point one replica at ``version``'s weights. For an engine-
        backed handle the engine itself rebinds — its busy guard
        refuses to swap under in-flight work, and the swap drops the
        old weights' KV (prefix cache + device splice memo) so stale
        blocks can never serve the new tree."""
        engine = getattr(handle, "engine", None)
        if engine is not None:
            engine.bind(params)
            engine.model_version = version
        if hasattr(handle, "params"):
            handle.params = params
        handle.version = version

    # -- evaluation --------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One decision per call; deterministic tests pass ``now``."""
        with self._eval_lock:
            return self._evaluate_locked(
                self._clock() if now is None else float(now)
            )

    def _evaluate_locked(self, now: float) -> dict:
        self._g_canaries.set(float(len(self._canaries)))
        if self._stage == "idle":
            return self._record("rollout_hold", "idle", {})
        detail = {"stage": self._stage, "version": self._version}
        if self._shadow_degrade_pending:
            # the shadow worker latched a degrade (wedged/dead canary):
            # surface it as THIS tick's decision so the flight ring
            # shows rollout_hold{shadow_degraded} exactly once
            self._shadow_degrade_pending = False
            return self._record("rollout_hold", "shadow_degraded", {
                **detail, "shadow_failures": self._shadow_failures,
            })
        signals = self.router.replica_signals()
        if self._stage == "provisioning":
            return self._provision_step(now, signals, detail)
        if self._stage == "baking":
            return self._bake_step(now, signals, detail)
        if self._stage == "promoting":
            return self._promote_step(now, signals, detail)
        raise AssertionError(f"unknown rollout stage {self._stage!r}")

    # -- stage: provisioning ----------------------------------------------

    def _provision_step(self, now, signals, detail) -> dict:
        p = self.policy
        if now < self._provision_retry_at:
            return self._record("rollout_hold", "provision_backoff", {
                **detail,
                "retry_in_s": round(self._provision_retry_at - now, 3),
            })
        name = f"{p.name_prefix}-{self._version}-{self._next_id}"
        try:
            handle = self.provisioner.provision(name)
        except BaseException as exc:
            return self._provision_failed(now, name, exc, detail)
        self._next_id += 1
        try:
            # bind BEFORE warming: bind() clears any factory-time
            # prefix cache, so imports after it survive
            self._bind_version(handle, self._params, self._version)
        except BaseException as exc:
            self._release(handle)
            return self._provision_failed(now, name, exc, detail)
        # fleet-warm the canary from the warmest LIVE donor (the
        # autoscaler's donor ranking: most resident cache blocks).
        # Best-effort — a failed warm joins cold, never blocks the join.
        imported, donor_name = 0, None
        live = {
            n: s for n, s in signals.items() if n not in self._canaries
        }
        if p.warm_blocks > 0 and live:
            donor_name = max(
                live, key=lambda n: (live[n]["cache_blocks"], n),
            )
            if live[donor_name]["cache_blocks"] <= 0:
                donor_name = None
        if donor_name is not None:
            try:
                donor = self.router.replica_handle(donor_name)
                entries = donor.export_hot_blocks(max_blocks=p.warm_blocks)
                imported = int(handle.import_cache_blocks(entries))
            except BaseException as exc:
                logger.info(
                    f"rollout: warm-join of {name} from {donor_name} "
                    f"failed ({exc!r}); canary joins cold"
                )
                imported = 0
        try:
            self.router.add_replica(handle)
        except BaseException as exc:
            self._release(handle)
            return self._provision_failed(now, name, exc, detail)
        self._provision_failures = 0
        self._provision_retry_at = float("-inf")
        self._canaries[name] = handle
        self._g_canaries.set(float(len(self._canaries)))
        if len(self._canaries) < p.canary_replicas:
            return self._record("rollout_advance", "canary_join", {
                **detail, "replica": name, "warmed_blocks": imported,
                "pool": len(self._canaries),
            })
        # pool complete: open the traffic split and the shadow lane
        self._stage = "baking"
        self.router.set_version_split(
            self._version, percent=self._percent,
            tenants=self._pin_tenants,
        )
        if p.shadow:
            self._enable_shadow()
        return self._record("rollout_advance", "canary_ready", {
            **detail, "stage": "baking", "replica": name,
            "warmed_blocks": imported, "pool": len(self._canaries),
            "percent": self._percent,
        })

    def _provision_failed(self, now, name, exc, detail) -> dict:
        p = self.policy
        self._provision_failures += 1
        backoff = min(
            p.provision_backoff_s * (2 ** (self._provision_failures - 1)),
            p.provision_backoff_max_s,
        )
        self._provision_retry_at = now + backoff
        logger.info(
            f"rollout: provision {name} failed ({exc!r}); retrying in "
            f"{backoff:.1f}s"
        )
        return self._record("rollout_hold", "provision_failed", {
            **detail, "replica": name,
            "error": f"{type(exc).__name__}: {exc}",
            "retry_in_s": round(backoff, 3),
        })

    # -- stage: baking -----------------------------------------------------

    def _bake_step(self, now, signals, detail) -> dict:
        p = self.policy
        dead = [
            n for n in self._canaries
            if n not in signals
            or signals[n]["state"] == "ejected"
            or signals[n]["health"].get("status") == "unreachable"
        ]
        if dead:
            self._dead_streak += 1
            self._clean_evals = 0
            # a canary the router can't reach can't serve shadows
            # either: degrade shadowing NOW (the worker would only
            # burn its failure budget finding out the hard way)
            if self._shadow_on:
                self._disable_shadow(degraded=True)
            if self._dead_streak >= p.canary_dead_evals:
                return self._rollback("canary_dead", {
                    **detail, "dead": dead, "evals": self._dead_streak,
                })
            return self._record("rollout_hold", "hysteresis", {
                **detail, "signal": "canary_dead", "dead": dead,
                "streak": self._dead_streak,
            })
        self._dead_streak = 0
        burn = max(
            (
                float(signals[n]["health"].get("burn", 0.0) or 0.0)
                for n in self._canaries
            ),
            default=0.0,
        )
        diverged_total = self._shadow_stats["diverged"]
        new_divergences = diverged_total - self._diverged_acked
        self._diverged_acked = diverged_total
        parity_bad = new_divergences > p.divergence_tolerance
        burn_bad = burn >= p.canary_burn_threshold
        if parity_bad or burn_bad:
            self._bad_streak += 1
            self._clean_evals = 0
            reason = "parity_regression" if parity_bad else "slo_burn"
            signal = {
                **detail, "burn": round(burn, 4),
                "divergences": new_divergences,
                "streak": self._bad_streak,
            }
            if self._bad_streak >= p.sustain_evals:
                return self._rollback(reason, signal)
            return self._record("rollout_hold", "hysteresis", {
                **signal, "signal": reason,
            })
        self._bad_streak = 0
        self._clean_evals += 1
        if self._clean_evals >= p.bake_evals and p.auto_promote:
            self._stage = "promoting"
            self._disable_shadow()
            return self._record("rollout_advance", "bake_complete", {
                **detail, "stage": "promoting",
                "clean_evals": self._clean_evals,
                "shadow": dict(self._shadow_stats),
            })
        return self._record("rollout_hold", "baking", {
            **detail, "clean_evals": self._clean_evals,
            "burn": round(burn, 4),
        })

    # -- stage: promoting --------------------------------------------------

    def _promote_step(self, now, signals, detail) -> dict:
        p = self.policy
        targets = sorted(
            n for n, s in signals.items()
            if n not in self._canaries
            and n not in self._promoted
            and getattr(
                self.router.replica_handle(n), "version", None
            ) != self._version
        )
        if targets:
            # one replica per tick: capacity dips by exactly one
            # replica at a time, and every step is a flight event
            name = targets[0]
            handle = self.router.replica_handle(name)
            old = {
                "params": getattr(handle, "params", None),
                "version": getattr(handle, "version", None),
            }
            if not self.router.drain_replica(name, timeout=p.drain_timeout_s):
                self.router.rejoin_replica(name)
                return self._record("rollout_hold", "drain_timeout", {
                    **detail, "replica": name,
                })
            try:
                self._bind_version(handle, self._params, self._version)
            except BaseException as exc:
                # bind's busy guard held (e.g. a preempted stream in
                # evict→resume limbo): the replica rejoins on the OLD
                # weights — correct, just not promoted yet
                self.router.rejoin_replica(name)
                return self._record("rollout_hold", "drain_timeout", {
                    **detail, "replica": name,
                    "error": f"{type(exc).__name__}: {exc}",
                })
            self.router.rejoin_replica(name)
            self._promoted[name] = old
            return self._record("rollout_advance", "promote_replica", {
                **detail, "replica": name,
                "remaining": len(targets) - 1,
            })
        if self._canaries:
            # all live replicas serve the new version: the split has
            # nothing left to split — retire it, then reap canaries
            # one per tick through the normal drain path
            self.router.clear_version_split()
            name = sorted(self._canaries)[0]
            self._reap_canary(name)
            return self._record("rollout_advance", "reap_canary", {
                **detail, "replica": name,
                "remaining": len(self._canaries),
            })
        version = self._version
        self.router.live_version = version
        self.router.clear_version_split()
        self._reset()
        return self._record("rollout_advance", "complete", {
            "version": version,
        })

    # -- rollback ----------------------------------------------------------

    def _rollback(self, reason: str, detail: dict) -> dict:
        """Tear the rollout down WITHOUT touching live capacity:
        shadow off, split cleared, canaries drained + released, and —
        mid-promote — already-promoted replicas walked back to the old
        weights through the same drain → bind → rejoin step."""
        self._disable_shadow()
        self.router.clear_version_split()
        restored, stuck = [], []
        for name, old in sorted(self._promoted.items()):
            try:
                handle = self.router.replica_handle(name)
                if not self.router.drain_replica(
                    name, timeout=self.policy.drain_timeout_s
                ):
                    raise RuntimeError("drain timed out")
                self._bind_version(
                    handle, old["params"],
                    old["version"] or self.router.live_version
                    or DEFAULT_MODEL_VERSION,
                )
                # an unversioned pre-rollout replica goes back to
                # carrying the fleet's implicit live version
                handle.version = old["version"]
                restored.append(name)
            except BaseException as exc:
                # degrade, don't wedge: the replica keeps serving the
                # NEW weights (it is healthy — the rollback was about
                # the canaries); the operator sees it in the detail
                stuck.append(name)
                logger.warning(
                    f"rollout: rollback could not restore {name} "
                    f"({exc!r}); it stays on {self._version}"
                )
            finally:
                try:
                    self.router.rejoin_replica(name)
                except BaseException:
                    pass
        reaped = [
            name for name in sorted(self._canaries)
            if self._reap_canary(name)
        ]
        version = self._version
        self._reset()
        out = {
            "version": version, **detail, "reaped": reaped,
        }
        if restored:
            out["restored"] = restored
        if stuck:
            out["stuck_on_new"] = stuck
        return self._record("rollout_rollback", reason, out)

    def _reap_canary(self, name: str) -> bool:
        handle = self._canaries.pop(name, None)
        self._g_canaries.set(float(len(self._canaries)))
        try:
            self.router.remove_replica(
                name, drain_timeout=self.policy.drain_timeout_s
            )
        except BaseException as exc:
            logger.warning(f"rollout: reap of {name} failed ({exc!r})")
        self._release(handle)
        return True

    def _release(self, handle) -> None:
        if handle is None:
            return
        try:
            self.provisioner.release(handle)
        except BaseException:
            pass

    def _reset(self) -> None:
        self._stage = "idle"
        self._version = None
        self._params = None
        self._canaries = {}
        self._promoted = {}
        self._bad_streak = self._clean_evals = self._dead_streak = 0
        self._g_canaries.set(0.0)

    # -- recording ---------------------------------------------------------

    def _record(self, decision: str, reason: str, detail: dict) -> dict:
        self._m_decisions.labels(decision, reason).inc()
        out = {"decision": decision, "reason": reason, **detail}
        self._last_decision = out
        if reason not in _STEADY_REASONS:
            self._history.append(out)
            self._flight.record(decision, reason=reason, **detail)
            # the fleet timeline: a latency spike and the rollout
            # decision around it sit on one trace axis
            self.router.trace_event(decision, reason=reason, **detail)
        return out

    # -- shadow lane -------------------------------------------------------

    def _enable_shadow(self) -> None:
        if self._shadow_on or self._shadow_degraded:
            return
        self._shadow_on = True
        self._shadow_stop.clear()
        if self._shadow_worker is None or not self._shadow_worker.is_alive():
            self._shadow_worker = threading.Thread(
                target=self._shadow_loop, name="rollout-shadow", daemon=True,
            )
            self._shadow_worker.start()

    def _disable_shadow(self, *, degraded: bool = False) -> None:
        was_on = self._shadow_on
        self._shadow_on = False
        self._shadow_stop.set()
        self._shadow_wake.set()
        with self._shadow_lock:
            dropped = len(self._shadow_q)
            self._shadow_q.clear()
        if dropped:
            self._shadow_stats["dropped"] += dropped
            self._m_shadow.labels("dropped").inc(dropped)
        if degraded and was_on and not self._shadow_degraded:
            self._shadow_degraded = True
            # surfaced as the next tick's rollout_hold{shadow_degraded}
            self._shadow_degrade_pending = True

    def observe_live(
        self, *, rid: str, replica: str, prompt: Sequence[int],
        max_new_tokens: Optional[int], tokens: List[int],
    ) -> None:
        """The router's post-success hook: enqueue one completed LIVE
        request for shadow dispatch onto the canary. Free-rider by
        construction — called after the live answer is fully emitted,
        never blocks (bounded queue, drop + count under burst), never
        raises into the dispatch path."""
        if not self._shadow_on or self._stage != "baking":
            return
        if replica in self._canaries:
            return   # canary-served requests have nothing to diff against
        p = self.policy
        with self._shadow_lock:
            if p.shadow_sample < 1.0:
                # deterministic stride sampling — no RNG, no wall
                # clock: every round(1/rate)-th live request shadows
                self._sample_n += 1
                stride = max(1, int(round(1.0 / p.shadow_sample)))
                if self._sample_n % stride:
                    return
            if len(self._shadow_q) >= p.shadow_queue:
                self._shadow_stats["dropped"] += 1
                self._m_shadow.labels("dropped").inc()
                return
            self._shadow_q.append((
                rid, list(prompt), max_new_tokens, list(tokens),
                telemetry.current_trace_context(),
            ))
        self._shadow_wake.set()

    def _shadow_loop(self) -> None:
        while not self._shadow_stop.is_set():
            self._shadow_wake.wait(timeout=0.2)
            while True:
                with self._shadow_lock:
                    if not self._shadow_q:
                        self._shadow_wake.clear()
                        break
                    item = self._shadow_q.popleft()
                try:
                    self._shadow_one(*item)
                except BaseException:
                    pass   # the loop itself must never die

    def _shadow_one(self, rid, prompt, max_new_tokens, live_tokens, ctx):
        canaries = list(self._canaries.items())
        if not canaries or not self._shadow_on:
            return
        name, handle = canaries[self._shadow_rr % len(canaries)]
        self._shadow_rr += 1
        tracer = self.router.tracer
        shadow_rid = None
        t0 = time.perf_counter()
        try:
            # the shadow runs in the LIVE request's trace (one stitched
            # GET /debug/trace?rid=<live rid> shows both), but under
            # its own tenant + low priority: the canary's ledger shows
            # where the load came from, live tenants are never billed,
            # and on a colocated host a shadow can never preempt live
            # work. The worker thread carries NO ambient deadline —
            # a burned live deadline must not fail the shadow.
            scope = (
                telemetry.trace_scope(ctx) if ctx is not None
                else model_version_scope(None)   # no-op context
            )
            with scope, tenant_scope(SHADOW_TENANT), priority_scope("low"):
                if tracer is not None:
                    shadow_rid = tracer.new_request(
                        "shadow", live_rid=rid, replica=name,
                        version=self._version,
                    )
                tokens = handle.generate(
                    prompt, max_new_tokens=max_new_tokens
                )
            t1 = time.perf_counter()
            result = "match" if list(tokens) == live_tokens else "diverged"
            if tracer is not None and shadow_rid is not None:
                tracer.record_span(
                    shadow_rid, "shadow", t0, t1, replica=name,
                    version=self._version, result=result,
                    live_rid=rid, shadow_tokens=len(tokens),
                )
            if result == "diverged":
                first = next(
                    (
                        i for i, (a, b) in enumerate(zip(tokens, live_tokens))
                        if a != b
                    ),
                    min(len(tokens), len(live_tokens)),
                )
                self._flight.record(
                    "rollout_shadow", rid=rid, replica=name,
                    version=self._version, result="diverged",
                    first_diff=first, live_tokens=len(live_tokens),
                    shadow_tokens=len(tokens),
                )
            self._shadow_stats[result] += 1
            self._m_shadow.labels(result).inc()
            self._shadow_failures = 0
        except BaseException as exc:
            self._shadow_stats["error"] += 1
            self._m_shadow.labels("error").inc()
            self._shadow_failures += 1
            logger.info(
                f"rollout: shadow dispatch to {name} failed ({exc!r}) "
                f"[{self._shadow_failures}/"
                f"{self.policy.shadow_degrade_failures}]"
            )
            if tracer is not None and shadow_rid is not None:
                tracer.record_span(
                    shadow_rid, "shadow", t0, time.perf_counter(),
                    replica=name, version=self._version, result="error",
                    error=type(exc).__name__, live_rid=rid,
                )
            if self._shadow_failures >= self.policy.shadow_degrade_failures:
                # a wedged/dead canary degrades shadowing to OFF —
                # never an error on the live path
                self._disable_shadow(degraded=True)
        finally:
            if tracer is not None and shadow_rid is not None:
                tracer.finish_request(shadow_rid)

    # -- observability -----------------------------------------------------

    def dashboard(self) -> dict:
        """The ``GET /debug/rollout`` body (also embedded in
        ``fleet_report()``): read-only, never blocks dispatch."""
        return {
            "stage": self._stage,
            "version": self._version,
            "live_version": getattr(self.router, "live_version", None),
            "canaries": sorted(self._canaries),
            "promoted": sorted(self._promoted),
            "split": getattr(self.router, "version_split", lambda: None)(),
            "shadow": {
                "on": self._shadow_on,
                "degraded": self._shadow_degraded,
                "queued": len(self._shadow_q),
                **dict(self._shadow_stats),
            },
            "streaks": {
                "bad": self._bad_streak,
                "clean": self._clean_evals,
                "dead": self._dead_streak,
            },
            "last_decision": self._last_decision,
            "history": list(self._history),
            "versions": {
                vid: info["metadata"]
                for vid, info in self.versions.versions().items()
            },
            "policy": {
                "canary_replicas": self.policy.canary_replicas,
                "canary_percent": self.policy.canary_percent,
                "bake_evals": self.policy.bake_evals,
                "sustain_evals": self.policy.sustain_evals,
            },
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self, interval_s: float = 1.0) -> None:
        """Run :meth:`evaluate` on a daemon ticker (production mode;
        tests drive ``evaluate(now=...)`` directly)."""
        if self._ticker is not None:
            return
        self._ticker_stop.clear()

        def _tick():
            while not self._ticker_stop.wait(interval_s):
                try:
                    self.evaluate()
                except BaseException:
                    logger.exception("rollout: evaluate failed")

        self._ticker = threading.Thread(
            target=_tick, name="rollout-ticker", daemon=True,
        )
        self._ticker.start()

    def stop(self) -> None:
        self._ticker_stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=5.0)
            self._ticker = None

    def close(self) -> None:
        self.stop()
        self._disable_shadow()
        if self._shadow_worker is not None:
            self._shadow_worker.join(timeout=5.0)
            self._shadow_worker = None


__all__ = [
    "DEFAULT_MODEL_VERSION",
    "ROLLOUT_DECISIONS",
    "ROLLOUT_REASONS",
    "RolloutController",
    "RolloutPolicy",
    "SHADOW_TENANT",
    "VersionRegistry",
    "current_model_version",
    "model_version_scope",
    "validate_model_version",
]
