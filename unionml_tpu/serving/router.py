"""Cluster front door: a fault-tolerant multi-replica router.

Everything below one process is production-grade — admission control,
circuit breaker, supervised recovery, tracing, SLO watchdog, per-tenant
metering — but a replica dying still means every client pointed at it
fails. This module is the tier above: a :class:`FleetRouter` fronts N
engine replicas (in-process handles first, HTTP upstreams behind the
same :class:`ReplicaHandle` interface) and makes the *fleet* survive
what one process cannot (docs/robustness.md "Fleet robustness").

Routing composes signals the stack already emits:

- **prefix-cache locality** — the replica holding the longest cached
  prefix of the prompt wins (SGLang-style; the read-only
  :meth:`~unionml_tpu.serving.prefix_cache.RadixPrefixCache.peek`
  probe, so scoring never distorts per-replica cache telemetry);
- **queue depth + breaker state** — from each replica's ``health()``;
- **SLO burn** — :meth:`~unionml_tpu.slo.SloWatchdog.burn_score`
  deprioritizes replicas burning error budget *before* they breach.

Every dispatch is wrapped in a robustness envelope:

- **retry policy** — exponential backoff + deterministic seeded jitter,
  honoring typed ``Retry-After`` hints, retrying only errors that are
  safe and useful to retry (a 422 or a deadline miss is not);
- **retry budget** — a fleet-wide token bucket (deposits a fraction of
  live traffic, each retry spends one token) so a degraded fleet sees
  bounded retry amplification instead of a melt-down retry storm;
- **hedging** (opt-in) — a second dispatch to a *different* replica
  once the first exceeds the observed latency quantile; first answer
  wins, the loser's stream is closed (→ engine-side abandonment);
- **passive outlier ejection** — consecutive failures eject a replica
  with exponential-cooldown hysteresis; after cooldown exactly one
  probe request flows half-open, success rejoins it, failure re-ejects
  with doubled cooldown;
- **drain/join choreography** — ``drain_replica()`` stops new routes,
  delegates to the replica's own ``drain()`` (PR 3) so in-flight
  streams finish, and ``rejoin_replica()`` resumes + re-admits it;
  when the live set thins below ``min_live`` the router itself answers
  ``degraded`` health instead of blackholing.

Context propagates through the hop: in-process replicas inherit the
caller thread's ``deadline_scope``/``tenant_scope``/``trace_scope``
(hedge threads re-open them), and :class:`HttpReplica` re-emits them as
``X-Deadline-Ms`` / ``X-Tenant-ID`` / ``traceparent`` / ``X-Request-ID``
headers — so PR 5's trace tree and PR 8's ledger span the fleet.

Observability: ``unionml_router_*`` series (per-replica route/retry/
hedge/eject counters, live-replica gauge, pick-latency histogram) and
flight-recorder ``route``/``retry``/``hedge``/``eject``/``probe``/
``rejoin``/``drain``/``join`` events make every failover explainable
post-hoc.
"""

from __future__ import annotations

import itertools
import json
import random
import threading
import time
import urllib.error
import urllib.request
from contextlib import contextmanager, nullcontext
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from unionml_tpu import telemetry
from unionml_tpu._logging import logger
from unionml_tpu.serving.faults import (
    DeadlineExceeded,
    EngineUnavailable,
    Overloaded,
    current_deadline_ms,
    deadline_scope,
)
from unionml_tpu.serving.scheduler import (
    DEFAULT_MODEL_VERSION,
    current_model_version,
    current_priority,
    current_token_cap,
    model_version_scope,
    priority_scope,
    token_cap_scope,
    validate_phase,
    validate_token_cap,
)
from unionml_tpu.serving.usage import current_tenant, tenant_scope

# the router's request id, exposed to replica dispatches on this thread
# (deadline-scope-style): HttpReplica re-emits it as X-Request-ID so the
# remote flight recorder tags the same rid and cross-hop correlation
# ("follow one request") works over HTTP replicas too
_rid_tls = threading.local()


@contextmanager
def _rid_scope(rid: str) -> Iterator[None]:
    prev = getattr(_rid_tls, "rid", None)
    _rid_tls.rid = rid
    try:
        yield
    finally:
        _rid_tls.rid = prev


def current_route_rid() -> Optional[str]:
    """The routing request id of the dispatch on this thread, if any."""
    return getattr(_rid_tls, "rid", None)


__all__ = [
    "EngineReplica",
    "FleetRouter",
    "HttpReplica",
    "ReplicaHandle",
    "RouterPolicy",
    "make_router_app",
]


class ReplicaHandle:
    """The interface one replica presents to the router.

    Subclass for each transport; :class:`EngineReplica` wraps an
    in-process :class:`~unionml_tpu.serving.engine.DecodeEngine`,
    :class:`HttpReplica` a remote serving process. All methods may be
    called concurrently from router worker threads.
    """

    name: str = "replica"

    # True for handles whose observability fetches cross a network
    # (the fleet debug surfaces fan those out on bounded-deadline
    # threads; in-process fetches run inline — a local registry read
    # must not pay a thread spawn per scrape)
    remote: bool = False

    # which serving phase this replica's pool owns (docs/serving.md
    # "Disaggregated serving"): "prefill" / "decode" / "colocated"
    # (default — serves both). The DisaggRouter's phase-aware pick
    # routes by it; fleet_report / GET /debug/fleet tag replicas with
    # it so the operator dashboard shows per-pool state.
    phase: str = "colocated"

    # which model version this replica serves (docs/robustness.md
    # "Rollouts & rollback"): None = the fleet's implicit live version
    # (the router substitutes its `live_version`). The RolloutController
    # stamps canaries and promoted replicas; the version-aware pick and
    # every observability surface key on it.
    version: Optional[str] = None

    def generate_stream(
        self, prompt: Sequence[int], *, max_new_tokens: Optional[int] = None,
    ) -> Iterator[List[int]]:
        """Yield token chunks for one prompt — the streaming dispatch
        primitive (hedged losers are cancelled by closing the
        iterator, and mid-stream failover replays past emitted
        chunks)."""
        raise NotImplementedError

    def generate(
        self, prompt: Sequence[int], *, max_new_tokens: Optional[int] = None,
    ) -> List[int]:
        """All tokens for one prompt, blocking — the non-streaming
        dispatch primitive. Default collects :meth:`generate_stream`;
        in-process replicas override with the engine's native blocking
        call (one event wait instead of per-chunk queue hops — the
        passthrough-overhead bench leg rides on this)."""
        out: List[int] = []
        for chunk in self.generate_stream(
            prompt, max_new_tokens=max_new_tokens
        ):
            out.extend(chunk)
        return out

    def health(self) -> dict:
        """The replica's ``/health`` dict: at least ``status`` and
        ``queue_depth``; ``burn`` (SLO burn score) when known."""
        raise NotImplementedError

    def cached_prefix_len(self, prompt: Sequence[int]) -> int:
        """Tokens of ``prompt`` this replica holds a cached KV prefix
        for (0 when unknown — remote replicas without a peek API)."""
        return 0

    def cache_blocks(self) -> int:
        """Resident prefix-cache blocks (0 when unknown) — the
        autoscaler's warm-donor/cold-victim ranking signal."""
        return 0

    def export_hot_blocks(self, max_blocks: int = 64) -> List[dict]:
        """The warm-join donor hook: this replica's hottest cached
        prefix blocks as :meth:`~unionml_tpu.serving.prefix_cache
        .RadixPrefixCache.export_hot` entries (empty when the replica
        has no exportable cache — remote replicas don't ship KV bytes
        over this API yet)."""
        return []

    def import_cache_blocks(self, entries: Sequence[dict]) -> int:
        """The warm-join import hook: attach a donor's exported blocks
        before this replica takes traffic; returns blocks attached (0
        when unsupported)."""
        return 0

    # -- disaggregated prefill/decode hooks (docs/serving.md
    # "Disaggregated serving"): the two-leg dispatch primitives. Every
    # implementation must either work or raise — the DisaggRouter
    # degrades a failed prefill leg to a cold decode-side prefill, so
    # none of these can ever cost a caller-visible failure.

    def prefill_export(
        self, prompt: Sequence[int], *, max_new_tokens: Optional[int] = None,
    ) -> dict:
        """Run prefill ONLY and finalize the prompt's KV into the
        replica's host block store; returns the KV handle
        (``{"tokens": [first], "cached_tokens": N, "lease": ...}`` —
        see :meth:`~unionml_tpu.serving.engine.DecodeEngine
        .prefill_export`). A replica that CANNOT serve a prefill leg
        (no prefix cache) raises the infra-class
        :class:`~unionml_tpu.serving.faults.EngineUnavailable` — a
        pool misconfiguration must degrade the request to a cold
        decode-side prefill, not surface as a caller error (the
        router re-raises only deterministic caller faults)."""
        raise EngineUnavailable(
            f"{self.name}: replica does not support prefill_export",
            reason="no_prefill",
        )

    def export_request_blocks(self, prompt: Sequence[int]) -> List[dict]:
        """The cross-store handoff donor hook: this replica's cached
        blocks covering ``prompt`` as importable entries
        (:meth:`~unionml_tpu.serving.prefix_cache.RadixPrefixCache
        .export_request`); empty when nothing is cached."""
        return []

    def kv_store(self):
        """The in-process :class:`~unionml_tpu.serving.prefix_cache
        .RadixPrefixCache` behind this replica, when one exists —
        identity comparison is how the router detects SAME-HOST pools
        sharing one store (pointer handoff, no transfer needed)."""
        return None

    # -- fleet observability hooks (docs/observability.md "Fleet
    # observability"): how the router app's federated /metrics, merged
    # /debug/flight, stitched /debug/trace, and fleet /debug/slo +
    # /debug/usage read THIS replica. Defaults say "nothing to
    # contribute"; every implementation must degrade (None/empty),
    # never raise — a dead replica degrades a debug surface, it does
    # not break it.

    def metrics_registry(self) -> Optional[telemetry.MetricsRegistry]:
        """The in-process registry behind :meth:`metrics_text`, when
        one exists — the router app skips replicas whose registry IS
        its own (their series are already in the local exposition)."""
        return None

    def metrics_text(self) -> Optional[str]:
        """This replica's Prometheus exposition body (``None`` =
        nothing to federate)."""
        return None

    def flight_recorder(self) -> Optional[telemetry.FlightRecorder]:
        """The in-process flight ring behind :meth:`flight_events`
        (identity with the router app's ring = already merged)."""
        return None

    def flight_events(self, n: Optional[int] = None) -> Optional[List[dict]]:
        """This replica's newest flight events (oldest first); ``[]``
        = genuinely empty ring, ``None`` = the fetch FAILED (the
        router app counts the failure — an empty ring and a dead
        replica must not read the same)."""
        return []

    def trace_recorder(self) -> Optional[telemetry.TraceRecorder]:
        """The in-process trace recorder behind :meth:`stitched_spans`
        (identity with the router app's recorder = already stitched)."""
        return None

    def stitched_spans(
        self, trace_id: str
    ) -> Optional[Tuple[List[dict], List[dict]]]:
        """``(spans, events)`` this replica holds for ``trace_id``, in
        :func:`~unionml_tpu.telemetry.stitched_trace` span form — the
        fetch half of cross-hop stitching. ``None`` = the fetch
        FAILED (counted by the router app), distinct from holding
        nothing for the trace."""
        return [], []

    def slo_report(self) -> Optional[dict]:
        """This replica's ``/debug/slo`` evaluation (``None`` when it
        runs no watchdog)."""
        return None

    def usage_ledger(self):
        """The in-process :class:`~unionml_tpu.serving.usage
        .UsageLedger` behind :meth:`usage_report`, when one exists —
        replicas sharing ONE ledger must be merged once, not per
        replica."""
        return None

    def usage_report(self) -> Optional[dict]:
        """This replica's ``/debug/usage`` body (``None`` when it
        meters nothing)."""
        return None

    def goodput_report(self) -> Optional[dict]:
        """This replica's ``/debug/goodput`` body — the serving perf
        plane's batch-occupancy report (``None`` when the replica runs
        no plane or the fetch failed)."""
        return None

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Finish in-flight work; stop admitting. True when drained."""
        return True

    def resume(self) -> None:
        """Reopen admissions after :meth:`drain`."""

    def close(self) -> None:
        """Release any resources the handle itself owns."""


class EngineReplica(ReplicaHandle):
    """An in-process :class:`~unionml_tpu.serving.engine.DecodeEngine`
    behind the replica interface.

    ``params`` are the replica's bound serving weights. ``slo`` is an
    optional per-replica :class:`~unionml_tpu.slo.SloWatchdog` whose
    :meth:`~unionml_tpu.slo.SloWatchdog.burn_score` rides the health
    dict as the router's load-shifting signal. Ambient deadline/tenant/
    trace scopes propagate by construction: the dispatch runs on the
    caller's (or hedge worker's re-scoped) thread.
    """

    def __init__(self, engine, params, *, name: str, slo=None,
                 phase: Optional[str] = None,
                 version: Optional[str] = None):
        self.engine = engine
        self.params = params
        self.name = name
        self._slo = slo
        # the model version these weights are (None = the fleet's live
        # version); stamped onto the engine so its usage vectors carry
        # the same tag
        self.version = version
        if version is not None:
            engine.model_version = version
        # phase defaults to the engine's own declaration, so a
        # DecodeEngine(phase="prefill") replica routes correctly
        # without repeating itself at wrap time
        self.phase = validate_phase(
            phase if phase is not None
            else getattr(engine, "phase", None)
        )

    def generate_stream(self, prompt, *, max_new_tokens=None):
        return self.engine.generate_stream(
            self.params, prompt, max_new_tokens=max_new_tokens
        )

    def generate(self, prompt, *, max_new_tokens=None):
        return self.engine.generate(
            self.params, [prompt], max_new_tokens=max_new_tokens
        )[0]

    def prefill_export(self, prompt, *, max_new_tokens=None):
        if getattr(self.engine, "prefix_cache", None) is None:
            # misconfigured pool member: speak the infra vocabulary so
            # the disagg router degrades instead of erroring the caller
            raise EngineUnavailable(
                f"{self.name}: engine has no prefix cache — cannot "
                "serve a prefill leg",
                reason="no_prefill",
            )
        return self.engine.prefill_export(self.params, prompt)

    def export_request_blocks(self, prompt) -> List[dict]:
        return self.engine.kv_export(prompt)

    def kv_store(self):
        return getattr(self.engine, "prefix_cache", None)

    def health(self) -> dict:
        out = dict(self.engine.health())
        if self._slo is not None:
            self._slo.evaluate()
            out["burn"] = self._slo.burn_score()
            breached = self._slo.breached()
            if breached and out.get("status") == "ok":
                out["status"] = "degraded"
        return out

    def cached_prefix_len(self, prompt) -> int:
        cache = getattr(self.engine, "prefix_cache", None)
        if cache is None:
            return 0
        return int(cache.peek(prompt))

    def cache_blocks(self) -> int:
        cache = getattr(self.engine, "prefix_cache", None)
        return 0 if cache is None else int(cache.entries)

    def export_hot_blocks(self, max_blocks: int = 64) -> List[dict]:
        cache = getattr(self.engine, "prefix_cache", None)
        if cache is None:
            return []
        return cache.export_hot(max_blocks=max_blocks)

    def import_cache_blocks(self, entries: Sequence[dict]) -> int:
        cache = getattr(self.engine, "prefix_cache", None)
        if cache is None:
            return 0
        return int(cache.import_blocks(entries))

    def metrics_registry(self):
        return self.engine.registry

    def metrics_text(self) -> Optional[str]:
        return self.engine.registry.exposition()

    def flight_recorder(self):
        return self.engine.flight

    def flight_events(self, n: Optional[int] = None) -> List[dict]:
        flight = self.engine.flight
        return [] if flight is None else flight.dump(n=n)

    def trace_recorder(self):
        return self.engine.tracer

    def stitched_spans(self, trace_id: str) -> Tuple[List[dict], List[dict]]:
        doc = telemetry.stitched_trace(
            trace_id, self.engine.tracer.requests_for_trace(trace_id)
        )
        return doc["spans"], doc["events"]

    def slo_report(self) -> Optional[dict]:
        return None if self._slo is None else self._slo.evaluate()

    def usage_ledger(self):
        return self.engine.usage

    def usage_report(self) -> Optional[dict]:
        ledger = self.engine.usage
        return None if ledger is None else ledger.report()

    def goodput_report(self) -> Optional[dict]:
        try:
            return self.engine.goodput_report()
        except ValueError:
            return None  # plane off on this engine: degrade, don't error

    def drain(self, timeout: Optional[float] = None) -> bool:
        return self.engine.drain(timeout)

    def resume(self) -> None:
        self.engine.resume()


class HttpReplica(ReplicaHandle):
    """A remote serving process (stdlib/FastAPI transport) behind the
    replica interface.

    Dispatch is ``POST {base_url}/predict/stream`` (SSE), health is
    ``GET /health``. Ambient scopes re-emit as headers — the remote
    transport re-opens them, so deadlines keep shedding, tenants keep
    getting billed, and the trace tree stays connected across the hop.
    Connection errors surface as :class:`~unionml_tpu.serving.faults
    .EngineUnavailable` (retryable); the typed 429/503/504 statuses map
    back to their local exceptions, ``Retry-After`` included, so the
    router's retry policy sees one error vocabulary for both replica
    kinds.
    """

    remote = True  # observability fetches cross the network: fan out

    def __init__(
        self, base_url: str, *, name: Optional[str] = None,
        timeout_s: float = 60.0, peek_ttl_s: float = 1.0,
        peek_cache_size: int = 256, peek_timeout_s: float = 2.0,
        peek_prompt_tokens: int = 128, metrics_ttl_s: float = 2.0,
        obs_timeout_s: float = 5.0, phase: Optional[str] = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.name = name if name is not None else self.base_url
        # a remote's phase is the OPERATOR's declaration (the process
        # behind the URL can't be introspected per pick): pass
        # phase="prefill"/"decode" when registering pool members
        self.phase = validate_phase(phase)
        self.timeout_s = timeout_s
        # remote cache-peek probe cache (health-TTL-style): the router
        # peeks per pick, and a per-pick HTTP round trip would make
        # every dispatch pay a network RTT per replica. Strict `<` so
        # peek_ttl_s=0 means always-fresh; bounded so a high-entropy
        # prompt stream can't grow host memory. The probe gets its OWN
        # short timeout (a peek must never stall a pick the way the
        # 60 s dispatch timeout would on a wedged-but-accepting host)
        # and keys/queries on only the first `peek_prompt_tokens`
        # tokens — affinity is a property of the PREFIX, so
        # unique-suffix traffic (the normal LLM workload) still hits
        # the cache, and probe URLs stay bounded for 100k-token
        # prompts.
        self.peek_ttl_s = float(peek_ttl_s)
        self.peek_timeout_s = float(peek_timeout_s)
        self.peek_prompt_tokens = int(peek_prompt_tokens)
        self._peek_cache_size = int(peek_cache_size)
        self._peek_cache: Dict[bytes, tuple] = {}
        self._peek_lock = threading.Lock()
        self._peek_supported = True  # flips off on a 404 (older remote)
        # metrics-federation scrape cache (health-TTL pattern, strict
        # `<` so metrics_ttl_s=0 means always-fresh): the router app's
        # /metrics federates every replica, so a hot scraper must not
        # fan out one remote GET per replica per scrape. On failure the
        # LAST-SEEN body keeps serving (a killed replica degrades the
        # fleet scrape to stale-or-absent series, never to an error).
        self.metrics_ttl_s = float(metrics_ttl_s)
        # the operator/debug fetch timeout (flight/slo/usage/trace
        # pulls): bounded so one wedged replica cannot stall a fleet
        # debug surface for the full 60 s dispatch timeout
        self.obs_timeout_s = float(obs_timeout_s)
        self._metrics_lock = threading.Lock()
        self._metrics_cache: Optional[str] = None
        self._metrics_at = float("-inf")

    def _headers(self) -> dict:
        headers = {"Content-Type": "application/json"}
        deadline_ms = current_deadline_ms()
        if deadline_ms is not None:
            headers["X-Deadline-Ms"] = str(deadline_ms)
        tenant = current_tenant()
        if tenant:
            headers["X-Tenant-ID"] = tenant
        # the scheduling class survives the hop: the remote transport
        # validates + re-opens it, so a routed high-priority request
        # keeps its preemption rights on the replica's engine
        headers["X-Priority"] = current_priority()
        # the model-version pin survives the hop too: a pinned request
        # routed through a fronting router must hit the same version
        # on the inner fleet (the X-Priority re-emission pattern)
        version = current_model_version()
        if version != DEFAULT_MODEL_VERSION:
            headers["X-Model-Version"] = version
        ctx = telemetry.current_trace_context()
        if ctx is not None:
            headers["traceparent"] = telemetry.format_traceparent(ctx)
        rid = current_route_rid()
        if rid:
            headers["X-Request-ID"] = rid
        return headers

    def _raise_typed(self, status: int, body: str, headers) -> None:
        retry_after = 1.0
        try:
            retry_after = float(headers.get("Retry-After", "1"))
        except (TypeError, ValueError):
            pass
        if status == 429:
            raise Overloaded(
                f"{self.name}: {body}", retry_after_s=retry_after
            )
        if status == 503:
            raise EngineUnavailable(
                f"{self.name}: {body}", retry_after_s=retry_after
            )
        if status == 504:
            raise DeadlineExceeded(f"{self.name}: {body}")
        if 400 <= status < 500:
            # a 4xx (e.g. 422 validation) is deterministic: the same
            # request fails on every replica — ValueError is the
            # NON-retryable class, so the router surfaces it instead
            # of burning budget re-sending a bad prompt
            raise ValueError(f"{self.name}: HTTP {status}: {body}")
        raise EngineUnavailable(  # other 5xx: possibly transient
            f"{self.name}: HTTP {status}: {body}",
            reason="http_error", retry_after_s=retry_after,
        )

    @staticmethod
    def _payload(prompt, max_new_tokens) -> dict:
        """The ``/predict``/``/predict/stream`` request body. The
        per-request token cap rides the payload's ``max_new_tokens``
        field (both transports parse it into a ``token_cap_scope``
        around the engine dispatch) — explicit argument first, else
        the ambient scope, mirroring how ``_headers`` re-emits the
        deadline/tenant scopes: a capped request keeps its cap across
        the hop, which failover token parity and the disaggregated
        two-leg dispatch both depend on."""
        payload = {"features": [list(int(t) for t in prompt)]}
        cap = (
            max_new_tokens if max_new_tokens is not None
            else current_token_cap()
        )
        if cap is not None:
            payload["max_new_tokens"] = int(cap)
        return payload

    def generate_stream(self, prompt, *, max_new_tokens=None):
        payload = self._payload(prompt, max_new_tokens)
        req = urllib.request.Request(
            f"{self.base_url}/predict/stream",
            data=json.dumps(payload).encode(),
            headers=self._headers(),
            method="POST",
        )
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout_s)
        except urllib.error.HTTPError as exc:
            body = exc.read().decode(errors="replace")
            self._raise_typed(exc.code, body, exc.headers)
        except (urllib.error.URLError, OSError, TimeoutError) as exc:
            raise EngineUnavailable(
                f"{self.name}: unreachable ({exc})", reason="unreachable",
            ) from exc
        return self._sse_chunks(resp)

    @staticmethod
    def _sse_chunks(resp) -> Iterator[List[int]]:
        """Decode the shared SSE wire protocol (one ``{"tokens"}``
        event per chunk, then ``{"done"}``) back into token chunks. A
        connection dropped before ``done`` raises — mid-stream replica
        death must surface as a retryable error, not silent
        truncation."""
        try:
            done = False
            for raw in resp:
                line = raw.decode(errors="replace").strip()
                if not line.startswith("data:"):
                    continue
                event = json.loads(line[len("data:"):])
                if event.get("done"):
                    done = True
                    return
                yield [int(t) for t in event["tokens"]]
            if not done:
                raise EngineUnavailable(
                    "stream dropped before done event",
                    reason="stream_dropped",
                )
        except (OSError, TimeoutError) as exc:
            raise EngineUnavailable(
                f"stream aborted mid-flight ({exc})", reason="stream_dropped",
            ) from exc
        finally:
            resp.close()

    def generate(self, prompt, *, max_new_tokens=None):
        payload = self._payload(prompt, max_new_tokens)
        req = urllib.request.Request(
            f"{self.base_url}/predict",
            data=json.dumps(payload).encode(),
            headers=self._headers(),
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                rows = json.loads(resp.read().decode())
        except urllib.error.HTTPError as exc:
            body = exc.read().decode(errors="replace")
            self._raise_typed(exc.code, body, exc.headers)
        except (urllib.error.URLError, OSError, TimeoutError) as exc:
            raise EngineUnavailable(
                f"{self.name}: unreachable ({exc})", reason="unreachable",
            ) from exc
        return [int(t) for t in rows[0]]

    def _get_json(
        self, path: str, timeout_s: Optional[float] = None
    ) -> dict:
        req = urllib.request.Request(f"{self.base_url}{path}")
        timeout = timeout_s if timeout_s is not None else self.timeout_s
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as exc:
            # /health answers 503 WITH the body when degraded/draining
            try:
                return json.loads(exc.read().decode())
            except (json.JSONDecodeError, OSError):
                raise EngineUnavailable(
                    f"{self.name}: HTTP {exc.code} on {path}",
                    reason="unreachable",
                ) from exc
        except (urllib.error.URLError, OSError, TimeoutError) as exc:
            raise EngineUnavailable(
                f"{self.name}: unreachable ({exc})", reason="unreachable",
            ) from exc

    def health(self) -> dict:
        # the control-plane read gets the bounded observability
        # timeout, not the 60 s dispatch timeout: health is probed on
        # the pick path (TTL-missed) and by /debug/fleet — a wedged-
        # but-accepting host must not stall either for a minute (the
        # same argument that gave the cache peek its own timeout)
        return self._get_json("/health", timeout_s=self.obs_timeout_s)

    def _get_debug_json(self, path: str) -> Optional[dict]:
        """Best-effort debug-surface fetch on the bounded
        ``obs_timeout_s``: any failure — unreachable host, 4xx (the
        surface isn't wired remotely), garbage body — answers ``None``
        so a fleet debug merge degrades instead of erroring."""
        try:
            req = urllib.request.Request(f"{self.base_url}{path}")
            with urllib.request.urlopen(
                req, timeout=self.obs_timeout_s
            ) as resp:
                return json.loads(resp.read().decode())
        except BaseException:
            return None

    def metrics_text(self) -> Optional[str]:
        """The remote ``GET /metrics`` body, TTL-cached
        (``metrics_ttl_s``, strict ``<``); failures serve the
        last-seen body (or ``None`` before the first success) — the
        federation contract: a killed replica degrades the fleet
        scrape, never breaks it."""
        now = time.monotonic()
        with self._metrics_lock:
            if now - self._metrics_at < self.metrics_ttl_s:
                return self._metrics_cache
        body: Optional[str] = None
        try:
            req = urllib.request.Request(f"{self.base_url}/metrics")
            with urllib.request.urlopen(
                req, timeout=self.obs_timeout_s
            ) as resp:
                body = resp.read().decode()
        except BaseException:
            body = None
        with self._metrics_lock:
            if body is not None:
                self._metrics_cache = body
            # a FAILED scrape also refreshes the TTL stamp — and the
            # stamp is taken AFTER the fetch: a black-holed host's
            # obs_timeout_s (5 s) exceeds metrics_ttl_s (2 s), so a
            # pre-fetch stamp would already be expired by the next
            # scrape and every fleet scrape would re-pay the full
            # connect timeout
            self._metrics_at = time.monotonic()
            return self._metrics_cache

    def flight_events(self, n: Optional[int] = None) -> Optional[List[dict]]:
        path = "/debug/flight" + (f"?n={int(n)}" if n is not None else "")
        body = self._get_debug_json(path)
        if body is None:
            return None  # fetch failed: the app counts it
        events = body.get("events", [])
        if not isinstance(events, list):
            return []
        # rebase the REMOTE host's monotonic t_ms onto the wall clock
        # using the anchor the remote computed itself — cross-host
        # monotonic readings are incomparable (each host's epoch is
        # its boot time); wall-anchored ones merge at NTP accuracy.
        # An older remote without the anchor returns raw readings
        # (degraded ordering, still merged).
        offset = body.get("wall_offset_ms")
        if isinstance(offset, (int, float)):
            events = [
                {**e, "t_ms": round(e.get("t_ms", 0.0) + offset, 3)}
                if isinstance(e, dict) else e
                for e in events
            ]
        return events

    def stitched_spans(
        self, trace_id: str
    ) -> Optional[Tuple[List[dict], List[dict]]]:
        body = self._get_debug_json(
            f"/debug/trace?trace={trace_id}&format=stitched"
        )
        if body is None:
            return None  # fetch failed: the app counts it
        return (
            body.get("spans", []) or [],
            body.get("events", []) or [],
        )

    def slo_report(self) -> Optional[dict]:
        return self._get_debug_json("/debug/slo")

    def usage_report(self) -> Optional[dict]:
        return self._get_debug_json("/debug/usage")

    def goodput_report(self) -> Optional[dict]:
        return self._get_debug_json("/debug/goodput")

    def cached_prefix_len(self, prompt) -> int:
        """Cache-affinity across hosts: probe the remote transport's
        ``GET /debug/cache/peek`` (the read-only peek the in-process
        path uses directly) with a TTL cache so the probe can never
        become a per-pick round trip, its own short ``peek_timeout_s``
        so it can never stall one either, and only the first
        ``peek_prompt_tokens`` tokens as the key AND the query (the
        affinity signal lives in the prefix — unique-suffix traffic
        still hits the cache). Any failure — unreachable host, a
        remote without the endpoint (HTTP 404, negative-cached
        permanently), no cache wired (422) — degrades to 0: affinity
        is an optimization, never a routing prerequisite."""
        if not self._peek_supported:
            return 0
        head = [int(t) for t in prompt[:self.peek_prompt_tokens]]
        key = b"".join(
            t.to_bytes(4, "little", signed=True) for t in head
        )
        now = time.monotonic()
        with self._peek_lock:
            hit = self._peek_cache.get(key)
            if hit is not None and now - hit[1] < self.peek_ttl_s:
                return hit[0]
        cached = 0
        url = (
            f"{self.base_url}/debug/cache/peek?prompt="
            + ",".join(str(t) for t in head)
        )
        try:
            with urllib.request.urlopen(
                urllib.request.Request(url), timeout=self.peek_timeout_s,
            ) as resp:
                body = json.loads(resp.read().decode())
            cached = int(body.get("cached_prefix_len", 0))
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                # the route itself is absent (any transport's 404
                # shape) — an older remote: stop asking forever
                self._peek_supported = False
                return 0
            cached = 0  # 422 (no cache wired) and other statuses
        except BaseException:
            cached = 0  # probe failures must never fail (or slow) a pick
        with self._peek_lock:
            if len(self._peek_cache) >= self._peek_cache_size:
                # bounded: drop the stalest ~half instead of growing
                cutoff = sorted(
                    at for _, at in self._peek_cache.values()
                )[len(self._peek_cache) // 2]
                self._peek_cache = {
                    k: v for k, v in self._peek_cache.items()
                    if v[1] > cutoff
                }
            self._peek_cache[key] = (cached, now)
        return cached

    def _post_json(
        self, path: str, body: dict, timeout_s: Optional[float] = None,
    ) -> dict:
        req = urllib.request.Request(
            f"{self.base_url}{path}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        timeout = timeout_s if timeout_s is not None else self.timeout_s
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as exc:
            text = exc.read().decode(errors="replace")
            self._raise_typed(exc.code, text, exc.headers)
        except (urllib.error.URLError, OSError, TimeoutError) as exc:
            raise EngineUnavailable(
                f"{self.name}: unreachable ({exc})", reason="unreachable",
            ) from exc

    def prefill_export(self, prompt, *, max_new_tokens=None):
        """The remote prefill leg: ONE 1-token ``/predict`` (the cap
        rides the payload) — the remote engine prefills, samples the
        first token, and finalizes the prompt's KV into ITS host
        store through the normal harvest path. The block entries only
        cross the wire later, if and when the decode side actually
        pulls them (:meth:`export_request_blocks`) — a same-fleet
        decode replica that turns out to share the store never pays
        the serialization."""
        out = self.generate(prompt, max_new_tokens=1)
        if not out:
            raise EngineUnavailable(
                f"{self.name}: empty prefill response",
                reason="http_error",
            )
        return {
            "tokens": out[:1],
            "prompt": [int(t) for t in prompt],
            # unknown from here — the transfer step discovers coverage
            "cached_tokens": 0,
            "lease": None,  # remote store: no local pin to hold
            "engine": self.name,
        }

    def _kv_export_wire(self, prompt) -> List[dict]:
        """The remote store's blocks covering ``prompt`` in WIRE form
        (``POST /debug/kv/export``, bounded by ``obs_timeout_s`` — a
        wedged prefill host must degrade the handoff to a cold decode
        prefill, not stall it for the dispatch timeout). The
        disaggregated router's remote→remote handoff relays this form
        untouched: transcoding megabytes of KV through numpy just to
        re-encode them would be pure churn on the handoff path."""
        body = self._post_json(
            "/debug/kv/export",
            {"prompt": [int(t) for t in prompt]},
            timeout_s=self.obs_timeout_s,
        )
        entries = body.get("entries", [])
        return entries if isinstance(entries, list) else []

    def _kv_import_wire(self, encoded: Sequence[dict]) -> int:
        """Push already-wire-form entries over ``POST
        /debug/kv/import``; returns blocks attached remotely."""
        if not encoded:
            return 0
        body = self._post_json(
            "/debug/kv/import", {"entries": list(encoded)},
            timeout_s=self.obs_timeout_s,
        )
        return int(body.get("attached", 0))

    def export_request_blocks(self, prompt) -> List[dict]:
        """The in-process entry form of :meth:`_kv_export_wire` (for
        an in-process importer on this side of the hop)."""
        from unionml_tpu.serving.prefix_cache import decode_entries

        return decode_entries(self._kv_export_wire(prompt))

    def import_cache_blocks(self, entries: Sequence[dict]) -> int:
        """Push block entries into the remote store over
        ``POST /debug/kv/import`` (the cross-host halves of both the
        KV handoff and fleet warming)."""
        from unionml_tpu.serving.prefix_cache import encode_entries

        if not entries:
            return 0
        return self._kv_import_wire(encode_entries(entries))

    def drain(self, timeout: Optional[float] = None) -> bool:
        # remote drain is an operator action on the remote process;
        # the router-side contract is just "stop routing here"
        return True


class RouterPolicy:
    """Tunables for :class:`FleetRouter` (one object so bench/test
    sweeps name their configuration in one place).

    Retry: up to ``max_attempts`` total dispatches per request,
    exponential backoff ``backoff_base_s * 2^(attempt-1)`` capped at
    ``backoff_max_s``, plus deterministic seeded jitter in
    ``[0, jitter_s)``; a typed ``Retry-After`` hint raises the floor.
    Retries draw on a fleet-wide budget: the bucket starts at
    ``retry_budget_burst`` tokens, each *admitted* request deposits
    ``retry_budget_ratio`` tokens (capped back at the burst), each
    retry spends one — so over any horizon
    ``retries <= burst + ratio * requests`` and a degraded fleet sees
    bounded amplification (Finagle/Envoy lineage; docs/robustness.md
    derives the bound).

    Hedging: off by default. When ``hedge=True``, a non-streaming
    request whose first dispatch exceeds the observed
    ``hedge_quantile`` latency (floored at ``hedge_min_s``, and only
    once ``hedge_warmup`` samples exist) dispatches once more to a
    different replica; first finished answer wins, the loser's stream
    is closed (engine-side abandonment reaps the slot). Hedges spend
    retry-budget tokens too — a hedge IS speculative retry load.

    Ejection: ``eject_consecutive`` consecutive retryable failures
    eject a replica for ``eject_cooldown_s``; each re-ejection doubles
    the cooldown (capped at ``eject_cooldown_max_s`` — the hysteresis
    that keeps a flapping replica from oscillating), a successful
    half-open probe rejoins it and resets the cooldown ladder.

    ``min_live``: below this many live replicas the router's own
    ``health()`` degrades — a thin fleet should shed at the balancer
    above, not blackhole at the router.

    Weighted least-request (``latency_weight``, default 0 = off): the
    router keeps a per-replica sliding window (``latency_window``
    samples, :class:`~unionml_tpu.telemetry.SlidingSamples`) of
    successful dispatch latencies and subtracts ``latency_weight *
    rolling_mean_seconds`` from the pick score — so a slow replica
    (overloaded host, thermal throttle, noisy neighbor) sheds share
    smoothly *without* waiting for failures to eject it. The weight is
    score-points per second: at the default queue_weight=2, a replica
    running 500 ms slower on average loses as much score as one extra
    queued request per ``latency_weight``.
    """

    def __init__(
        self,
        *,
        max_attempts: int = 3,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        jitter_s: float = 0.02,
        retry_budget_ratio: float = 0.2,
        retry_budget_burst: float = 3.0,
        hedge: bool = False,
        hedge_quantile: float = 0.95,
        hedge_min_s: float = 0.05,
        hedge_warmup: int = 20,
        eject_consecutive: int = 3,
        eject_cooldown_s: float = 5.0,
        eject_cooldown_max_s: float = 60.0,
        min_live: int = 1,
        cache_weight: float = 1.0,
        queue_weight: float = 2.0,
        burn_weight: float = 4.0,
        latency_weight: float = 0.0,
        latency_window: int = 128,
        health_ttl_s: float = 0.25,
        seed: int = 0,
    ):
        if latency_weight < 0.0:
            raise ValueError(
                f"latency_weight must be >= 0, got {latency_weight}"
            )
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        if not 0.0 <= retry_budget_ratio <= 1.0:
            raise ValueError(
                f"retry_budget_ratio must be in [0, 1], got "
                f"{retry_budget_ratio}"
            )
        if not 0.0 < hedge_quantile < 1.0:
            raise ValueError(
                f"hedge_quantile must be in (0, 1), got {hedge_quantile}"
            )
        if eject_consecutive < 1:
            raise ValueError(
                f"eject_consecutive must be >= 1, got {eject_consecutive}"
            )
        self.max_attempts = max_attempts
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.jitter_s = jitter_s
        self.retry_budget_ratio = retry_budget_ratio
        self.retry_budget_burst = retry_budget_burst
        self.hedge = hedge
        self.hedge_quantile = hedge_quantile
        self.hedge_min_s = hedge_min_s
        self.hedge_warmup = hedge_warmup
        self.eject_consecutive = eject_consecutive
        self.eject_cooldown_s = eject_cooldown_s
        self.eject_cooldown_max_s = eject_cooldown_max_s
        self.min_live = min_live
        self.cache_weight = cache_weight
        self.queue_weight = queue_weight
        self.burn_weight = burn_weight
        self.latency_weight = latency_weight
        self.latency_window = latency_window
        self.health_ttl_s = health_ttl_s
        self.seed = seed


# replica lifecycle states the router tracks (the replica's OWN health
# is a separate, composed signal)
_LIVE = "live"
_EJECTED = "ejected"
_HALF_OPEN = "half_open"
_DRAINING = "draining"


class _ReplicaState:
    """Router-side bookkeeping for one replica (all mutation under the
    router lock)."""

    __slots__ = (
        "handle", "state", "consecutive_failures", "eject_count",
        "rejoin_at", "probe_inflight", "health_cache", "health_at",
    )

    def __init__(self, handle: ReplicaHandle):
        self.handle = handle
        self.state = _LIVE
        self.consecutive_failures = 0
        self.eject_count = 0           # lifetime ejections → cooldown ladder
        self.rejoin_at = 0.0           # monotonic time the cooldown ends
        self.probe_inflight = False    # half-open: exactly one probe
        self.health_cache: dict = {}
        self.health_at = float("-inf")


def _retryable(exc: BaseException) -> bool:
    """Errors worth retrying on ANOTHER replica: overload/unavailable/
    transport failures and engine-side crashes. NOT retryable: the
    caller's own deadline (a second attempt arrives just as late),
    and validation errors (deterministically wrong on every
    replica)."""
    if isinstance(exc, (Overloaded, EngineUnavailable, TimeoutError)):
        # DeadlineExceeded subclasses TimeoutError — exclude it
        return not isinstance(exc, DeadlineExceeded)
    return isinstance(exc, RuntimeError) and not isinstance(exc, ValueError)


class FleetRouter:
    """Routes requests over N :class:`ReplicaHandle` s with failover,
    retry budgets, optional hedging, outlier ejection, and drain/join
    choreography (module docstring has the full story).

    ``clock`` is injectable (monotonic seconds) so ejection-cooldown
    tests are deterministic; production uses ``time.monotonic``.
    ``sleep`` likewise for backoff.
    """

    # fleet lifecycle events per fleet-timeline rotation: the timeline
    # must FINISH to export (OTLP listeners fire on finish), so a busy
    # fleet rotates often enough that events ship within minutes while
    # a quiet one holds a mostly-empty timeline open
    FLEET_TIMELINE_ROTATE = 256

    def __init__(
        self,
        replicas: Sequence[ReplicaHandle],
        *,
        policy: Optional[RouterPolicy] = None,
        registry: Optional[telemetry.MetricsRegistry] = None,
        flight: Optional[telemetry.FlightRecorder] = None,
        tracer: Optional[telemetry.TraceRecorder] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique, got {names}")
        self.policy = policy if policy is not None else RouterPolicy()
        self._clock = clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._replicas: Dict[str, _ReplicaState] = {
            r.name: _ReplicaState(r) for r in replicas
        }
        self._rr = 0  # round-robin tie-break counter
        self._draining = False
        self._rng = random.Random(self.policy.seed)
        self._budget_tokens = self.policy.retry_budget_burst
        self._latency = telemetry.SlidingSamples(maxlen=512)
        # per-replica dispatch-latency windows (the weighted
        # least-request term; populated lazily on first success)
        self._replica_latency: Dict[str, telemetry.SlidingSamples] = {}
        self._registry = (
            registry if registry is not None else telemetry.get_registry()
        )
        self._flight = (
            flight if flight is not None else telemetry.get_flight_recorder()
        )
        # the stitching recorder: every routed request opens a "route"
        # timeline here (pick / attempt / backoff / hedge-lane spans)
        # parented into the caller's ambient trace scope, and each
        # attempt's child context propagates to the replica — assign
        # None to the `tracer` property to turn the plane off (the
        # bench's paired-leg seam)
        self._tracer = tracer if tracer is not None else telemetry.get_tracer()
        self._fleet_lock = threading.Lock()
        self._fleet_rid: Optional[str] = None
        self._fleet_events = 0
        # set by a FleetAutoscaler operating this router; the fleet
        # dashboard (GET /debug/fleet) reads its last decision through
        # it. A phase-split fleet runs one autoscaler PER POOL (TTFT
        # burn scales prefill, decode headroom scales decode) — each
        # registers under its phase in `autoscalers`, and `autoscaler`
        # keeps pointing at the most recent registration (the single-
        # pool back-compat view).
        self.autoscaler = None
        self.autoscalers: Dict[str, object] = {}
        # model-version rollout state (docs/robustness.md "Rollouts &
        # rollback"): `rollout` is set by a RolloutController operating
        # this router (fleet_report / GET /debug/rollout read through
        # it, and every successful live dispatch offers itself for
        # shadowing through it). `live_version` is the version every
        # version-less replica implicitly serves; the split steers a
        # percentage / per-tenant slice of UNPINNED traffic to the
        # canary version while a rollout bakes.
        self.rollout = None
        self.live_version: Optional[str] = None
        self._version_split: Optional[dict] = None
        self._split_counter = 0
        self._build_instruments()
        self._g_live.set_function(self._live_count)

    @property
    def tracer(self) -> Optional[telemetry.TraceRecorder]:
        """The recorder routing timelines land in (``None`` = trace
        stitching off)."""
        return self._tracer

    @tracer.setter
    def tracer(self, recorder: Optional[telemetry.TraceRecorder]) -> None:
        """Swap (or disable, with ``None``) the stitching recorder —
        ONLY while idle, the same contract as ``engine.usage``: the
        ``serve_fleet_obs`` bench toggles this between its paired
        overhead legs so both run on the SAME router instance."""
        with self._fleet_lock:
            old_rid, old = self._fleet_rid, self._tracer
            self._fleet_rid = None
            self._fleet_events = 0
            self._tracer = recorder
        if old_rid is not None and old is not None:
            old.finish_request(old_rid)

    # -- instruments -------------------------------------------------------

    def _build_instruments(self) -> None:
        reg = self._registry
        self._m_routed = reg.counter(
            "unionml_router_requests_total",
            "Requests dispatched by the fleet router, by replica and "
            "outcome (ok/error/retried_away).",
            ("replica", "outcome"),
        )
        self._m_retries = reg.counter(
            "unionml_router_retries_total",
            "Retry dispatches, by the replica the retry was sent TO.",
            ("replica",),
        )
        self._m_hedges = reg.counter(
            "unionml_router_hedges_total",
            "Hedge dispatches, by replica and result (win/lose).",
            ("replica", "result"),
        )
        self._m_ejections = reg.counter(
            "unionml_router_ejections_total",
            "Outlier ejections, by replica.",
            ("replica",),
        )
        self._m_rejoins = reg.counter(
            "unionml_router_rejoins_total",
            "Replicas rejoined after a successful half-open probe or "
            "drain cycle, by replica.",
            ("replica",),
        )
        self._m_budget_exhausted = reg.counter(
            "unionml_router_retry_budget_exhausted_total",
            "Retries NOT attempted because the fleet-wide retry budget "
            "was empty (the storm-control activation count).",
        )
        self._g_live = reg.gauge(
            "unionml_router_live_replicas",
            "Replicas currently routable (live or half-open probing).",
        )
        self._h_pick_ms = reg.histogram(
            "unionml_router_pick_ms",
            "Replica-selection latency (health peeks + cache peeks + "
            "scoring).",
        )

    def _live_count(self) -> float:
        with self._lock:
            return float(sum(
                1 for s in self._replicas.values()
                if s.state in (_LIVE, _HALF_OPEN)
            ))

    # -- fleet lifecycle timeline ------------------------------------------

    def trace_event(self, name: str, **args) -> None:
        """Record one fleet-lifecycle instant — the router's
        ``eject``/``probe``/``rejoin`` transitions, the autoscaler's
        ``scale_*`` decisions — onto a rotating ``kind="fleet"``
        recorder timeline, exported over OTLP as span EVENTS on the
        fleet root span: a latency spike is then explainable from the
        trace alone, with the scale/eject marks sitting on the same
        wall-anchored axis as the request spans. Rotates every
        :data:`FLEET_TIMELINE_ROTATE` events (a finished timeline is
        what actually exports); no-op while stitching is off."""
        tracer = self._tracer
        if tracer is None:
            return
        finish_rid = None
        with self._fleet_lock:
            if tracer is not self._tracer:
                return  # swapped between the read and the lock
            if (
                self._fleet_rid is None
                or self._fleet_events >= self.FLEET_TIMELINE_ROTATE
            ):
                finish_rid = self._fleet_rid
                # trace_scope(None) masks any ambient request scope:
                # the fleet timeline is a ROOT trace, not a child of
                # whichever request's thread happened to eject first
                with telemetry.trace_scope(None):
                    self._fleet_rid = tracer.new_request(
                        "fleet", component="router",
                    )
                self._fleet_events = 0
            self._fleet_events += 1
            rid = self._fleet_rid
        if finish_rid is not None:
            tracer.finish_request(finish_rid)
        tracer.record_event(rid, name, **args)

    def _close_fleet_timeline(self) -> None:
        with self._fleet_lock:
            rid, self._fleet_rid = self._fleet_rid, None
            self._fleet_events = 0
            tracer = self._tracer
        if rid is not None and tracer is not None:
            tracer.finish_request(rid)

    # -- model-version routing (docs/robustness.md "Rollouts & rollback") --

    def _replica_version(self, handle: ReplicaHandle) -> Optional[str]:
        """The version ``handle`` serves: its own stamp, else the
        fleet's implicit live version."""
        return getattr(handle, "version", None) or self.live_version

    def set_version_split(
        self, version: str, *, percent: float = 0.0,
        tenants: Optional[Dict[str, str]] = None,
    ) -> None:
        """Steer a slice of UNPINNED traffic to ``version``:
        ``percent`` of requests (deterministic stride — no RNG, so
        chaos tests replay exactly) plus every request from a tenant
        in ``tenants`` (tenant → version). Split assignment is SOFT —
        when no routable replica serves the split version, the pick
        falls back to live capacity (a dying canary sheds its share,
        it never fails a caller). A hard ``X-Model-Version`` pin
        bypasses the split entirely."""
        if not 0.0 <= float(percent) <= 100.0:
            raise ValueError(
                f"split percent must be in [0, 100], got {percent}"
            )
        with self._lock:
            self._version_split = {
                "version": version,
                "percent": float(percent),
                "tenants": dict(tenants or {}),
            }
            self._split_counter = 0

    def clear_version_split(self) -> None:
        with self._lock:
            self._version_split = None

    def version_split(self) -> Optional[dict]:
        """The active split spec (a copy), or ``None``."""
        with self._lock:
            split = self._version_split
            return None if split is None else {
                "version": split["version"],
                "percent": split["percent"],
                "tenants": dict(split["tenants"]),
            }

    def _resolve_route_version(
        self,
    ) -> Tuple[Optional[str], bool, Optional[str]]:
        """``(version, soft, exclude_version)`` for one request: a
        hard ``X-Model-Version`` pin wins (strict — an unknown version
        is a 422, an unroutable one a 503), else the rollout split
        assigns softly (percentage stride / tenant pin, falling back
        to live when the canary is unroutable). Unpinned traffic the
        split did NOT assign carries the split version as a soft
        EXCLUSION — the canary gets exactly its share, never
        load-balancer spillover on top of it."""
        pin = current_model_version()
        if pin != DEFAULT_MODEL_VERSION:
            return pin, False, None
        with self._lock:
            split = self._version_split
            if split is None:
                return None, True, None
            tenant = current_tenant()
            if tenant in split["tenants"]:
                return split["tenants"][tenant], True, None
            percent = split["percent"]
            if percent > 0.0:
                self._split_counter += 1
                # deterministic percentage stride over the unit circle:
                # floor(c*p/100) advances exactly on the canary's share
                c = self._split_counter
                if (c * percent) // 100.0 > ((c - 1) * percent) // 100.0:
                    return split["version"], True, None
            return None, True, split["version"]

    # -- membership / choreography ----------------------------------------

    def members(self) -> Dict[str, ReplicaHandle]:
        """Every registered replica handle by name (any lifecycle
        state) — the fleet observability surfaces iterate membership
        through this instead of reaching into router internals."""
        with self._lock:
            return {n: s.handle for n, s in self._replicas.items()}

    def fleet_report(self) -> dict:
        """The ``GET /debug/fleet`` operator dashboard: per-replica
        router state + health (breaker, drain, queue depth), cache
        blocks, burn scores, retry-budget level — and, when a
        :class:`~unionml_tpu.serving.autoscaler.FleetAutoscaler`
        operates this router, its dashboard (usage headroom, burn
        windows, last scale decision + reason) under
        ``"autoscaler"``."""
        signals = self.replica_signals()  # ONE health sweep, TTL-cached
        health = self.health()            # router-local state, no probes
        with self._lock:
            budget = self._budget_tokens
        replicas = {}
        phases: Dict[str, dict] = {}
        for name, s in signals.items():
            h = s["health"]
            phase = s.get("phase", "colocated")
            replicas[name] = {
                "state": s["state"],
                "phase": phase,
                "version": s.get("version"),
                "status": h.get("status", "unknown"),
                "queue_depth": h.get("queue_depth", 0),
                "breaker_open": bool(h.get("breaker_open", False)),
                "burn": float(h.get("burn", 0.0) or 0.0),
                "cache_blocks": s["cache_blocks"],
                "consecutive_failures": s["consecutive_failures"],
            }
            # per-pool rollup: the operator dashboard's phase-split
            # view (docs/serving.md "Disaggregated serving")
            pool = phases.setdefault(
                phase, {"replicas": 0, "routable": 0, "queue_depth": 0},
            )
            pool["replicas"] += 1
            if s["state"] in (_LIVE, _HALF_OPEN):
                pool["routable"] += 1
            pool["queue_depth"] += int(h.get("queue_depth", 0) or 0)
        report = {
            "status": health["status"],
            "live_replicas": health["live_replicas"],
            "min_live": health["min_live"],
            "live_version": self.live_version,
            "retry_budget_tokens": round(budget, 3),
            "replicas": replicas,
            "phases": phases,
        }
        rollout = self.rollout
        if rollout is not None:
            try:
                report["rollout"] = rollout.dashboard()
            except BaseException as exc:
                # a mid-teardown controller degrades the dashboard,
                # never breaks /debug/fleet
                report["rollout"] = {"error": str(exc)}
        auto = self.autoscaler
        if auto is not None:
            try:
                # hand over the sweep this call already did, so the
                # dashboard costs zero additional health probes
                report["autoscaler"] = auto.dashboard(signals=signals)
            except BaseException as exc:
                # the dashboard is a debug read: a mid-teardown
                # autoscaler degrades it, never breaks /debug/fleet
                report["autoscaler"] = {"error": str(exc)}
        if len(self.autoscalers) > 1:
            # phase-split fleets: every pool's autoscaler view, keyed
            # by the phase it operates
            per_pool = {}
            for key, pool_auto in list(self.autoscalers.items()):
                try:
                    per_pool[key] = pool_auto.dashboard(signals=signals)
                except BaseException as exc:
                    per_pool[key] = {"error": str(exc)}
            report["autoscalers"] = per_pool
        return report

    def replica_handle(self, name: str) -> ReplicaHandle:
        """The handle registered under ``name`` (KeyError when absent)
        — the autoscaler uses this to reach a warm-join donor's
        export hook without holding router internals."""
        with self._lock:
            state = self._replicas.get(name)
            if state is None:
                raise KeyError(f"unknown replica {name!r}")
            return state.handle

    def add_replica(self, handle: ReplicaHandle) -> None:
        """Join a new replica into the live set (scale-out, or a
        rebuilt process re-registering)."""
        with self._lock:
            if handle.name in self._replicas:
                raise ValueError(f"replica {handle.name!r} already present")
            self._replicas[handle.name] = _ReplicaState(handle)
        self._flight.record("join", replica=handle.name)

    def remove_replica(self, name: str, *, drain_timeout: float = 30.0) -> bool:
        """Permanently remove ``name``: drain it first (in-flight
        streams finish), then drop it from the set. True when the
        drain completed within ``drain_timeout``."""
        drained = self.drain_replica(name, timeout=drain_timeout)
        with self._lock:
            self._replicas.pop(name, None)
            self._replica_latency.pop(name, None)
        self._flight.record("leave", replica=name, drained=drained)
        return drained

    def drain_replica(self, name: str, timeout: Optional[float] = None) -> bool:
        """Stop routing new work to ``name`` and delegate to the
        replica's own ``drain()`` so in-flight streams finish. The
        replica stays in the set (``rejoin_replica`` reverses); True
        when its drain reported complete."""
        with self._lock:
            state = self._replicas.get(name)
            if state is None:
                raise KeyError(f"unknown replica {name!r}")
            state.state = _DRAINING
        self._flight.record("drain", replica=name)
        try:
            return bool(state.handle.drain(timeout))
        except BaseException as exc:
            # a dead replica's drain dying with its process must not
            # wedge choreography (the autoscaler reaps through here)
            logger.info(f"router: drain of {name} failed ({exc!r})")
            return False

    def rejoin_replica(self, name: str) -> None:
        """Resume a drained replica and route to it again (the join
        half of rolling-restart choreography). Clears ejection
        bookkeeping: an operator rejoin is a statement the replica is
        believed healthy."""
        with self._lock:
            state = self._replicas.get(name)
            if state is None:
                raise KeyError(f"unknown replica {name!r}")
            state.handle.resume()
            state.state = _LIVE
            state.consecutive_failures = 0
            state.eject_count = 0
            state.probe_inflight = False
            state.health_at = float("-inf")
        self._m_rejoins.labels(name).inc()
        self._flight.record("rejoin", replica=name, cause="operator")
        self.trace_event("rejoin", replica=name, cause="operator")

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Drain the WHOLE fleet (router stops admitting; every replica
        drains). Reversible with :meth:`resume`."""
        self._draining = True
        with self._lock:
            states = list(self._replicas.values())
        ok = True
        for state in states:
            with self._lock:
                state.state = _DRAINING
            self._flight.record("drain", replica=state.handle.name)
            ok = bool(state.handle.drain(timeout)) and ok
        return ok

    def resume(self) -> None:
        """Reopen the router and every drained replica."""
        self._draining = False
        with self._lock:
            names = [
                n for n, s in self._replicas.items() if s.state == _DRAINING
            ]
        for name in names:
            self.rejoin_replica(name)

    def close(self) -> None:
        # flush the pending fleet-lifecycle events to any exporter
        self._close_fleet_timeline()
        for state in list(self._replicas.values()):
            state.handle.close()

    # -- health / stats ----------------------------------------------------

    def health(self) -> dict:
        """The router's OWN readiness: ``ok`` while at least
        ``policy.min_live`` replicas are routable, ``degraded`` below
        the floor (shed at the balancer above instead of blackholing
        here), ``draining`` during a fleet drain. Per-replica states
        ride along for operators."""
        with self._lock:
            replicas = {
                name: {
                    "state": s.state,
                    "consecutive_failures": s.consecutive_failures,
                    "eject_count": s.eject_count,
                }
                for name, s in self._replicas.items()
            }
            live = sum(
                1 for s in self._replicas.values()
                if s.state in (_LIVE, _HALF_OPEN)
            )
        if self._draining:
            status = "draining"
        elif live < self.policy.min_live:
            status = "degraded"
        else:
            status = "ok"
        return {
            "status": status,
            "live_replicas": live,
            "min_live": self.policy.min_live,
            "replicas": replicas,
        }

    def stats(self) -> dict:
        with self._lock:
            budget = self._budget_tokens
            replicas = {
                name: {
                    "state": s.state,
                    "consecutive_failures": s.consecutive_failures,
                    "eject_count": s.eject_count,
                }
                for name, s in self._replicas.items()
            }
        return {
            "engine": "router",
            "router": {
                "replicas": replicas,
                "retry_budget_tokens": round(budget, 3),
                "hedge_delay_s": round(self._hedge_delay_s(), 4),
                "latency_samples": len(self._latency),
            },
        }

    def replica_signals(self) -> Dict[str, dict]:
        """Per-replica router lifecycle state + the replica's OWN
        health (through the TTL cache, so polling this costs what a
        pick costs) + resident cache-block count — the autoscaler's
        one-stop signal read: queue depths, breaker states, burn
        scores, and cache warmth in one pass, without reaching into
        router internals."""
        now = self._clock()
        with self._lock:
            states = list(self._replicas.values())
        out: Dict[str, dict] = {}
        for state in states:
            health = self._health_of(state, now)
            try:
                blocks = int(state.handle.cache_blocks())
            except BaseException:
                blocks = 0
            out[state.handle.name] = {
                "state": state.state,
                "phase": getattr(state.handle, "phase", "colocated"),
                "version": self._replica_version(state.handle),
                "health": dict(health),
                "cache_blocks": blocks,
                "consecutive_failures": state.consecutive_failures,
            }
        return out

    def cached_prefix_len(self, prompt: Sequence[int]) -> int:
        """Fleet-wide longest cached prefix: the max over routable
        replicas' peeks. This is the router app's
        ``GET /debug/cache/peek`` source, so a router can front
        another router (or a balancer can probe a whole fleet) with
        cache affinity intact."""
        with self._lock:
            states = [
                s for s in self._replicas.values()
                if s.state in (_LIVE, _HALF_OPEN)
            ]
        best = 0
        for state in states:
            try:
                best = max(best, int(state.handle.cached_prefix_len(prompt)))
            except BaseException:
                continue  # a peek failure must never fail the probe
        return best

    def _notify_rollout(
        self, rid: str, name: str, prompt, max_new_tokens,
        tokens: List[int],
    ) -> None:
        """Offer one completed live dispatch to the rollout controller
        for shadowing. Never raises into the dispatch path, and costs
        one attribute read when no rollout is operating."""
        rollout = self.rollout
        if rollout is None:
            return
        try:
            rollout.observe_live(
                rid=rid, replica=name, prompt=prompt,
                max_new_tokens=max_new_tokens, tokens=tokens,
            )
        except BaseException:
            pass

    def _note_latency(self, name: str, seconds: float) -> None:
        """One successful dispatch's wall time: feeds the fleet-wide
        hedge-delay window AND the replica's least-request window."""
        self._latency.add(seconds)
        with self._lock:
            samples = self._replica_latency.get(name)
            if samples is None:
                samples = telemetry.SlidingSamples(
                    maxlen=self.policy.latency_window
                )
                self._replica_latency[name] = samples
        samples.add(seconds)

    # -- retry budget ------------------------------------------------------

    def _deposit_budget(self) -> None:
        with self._lock:
            self._budget_tokens = min(
                self.policy.retry_budget_burst,
                self._budget_tokens + self.policy.retry_budget_ratio,
            )

    def _spend_budget(self) -> bool:
        with self._lock:
            if self._budget_tokens >= 1.0:
                self._budget_tokens -= 1.0
                return True
        self._m_budget_exhausted.inc()
        return False

    # -- ejection lifecycle ------------------------------------------------

    def _record_failure(self, name: str, exc: BaseException) -> None:
        with self._lock:
            state = self._replicas.get(name)
            if state is None:
                return
            state.consecutive_failures += 1
            if state.state == _HALF_OPEN:
                # failed probe: immediately re-eject, doubled cooldown
                state.probe_inflight = False
                self._eject_locked(state, cause="probe_failed")
                return
            if (
                state.state == _LIVE
                and state.consecutive_failures >= self.policy.eject_consecutive
            ):
                self._eject_locked(state, cause=type(exc).__name__)

    def _eject_locked(self, state: _ReplicaState, *, cause: str) -> None:
        state.eject_count += 1
        cooldown = min(
            self.policy.eject_cooldown_s * (2 ** (state.eject_count - 1)),
            self.policy.eject_cooldown_max_s,
        )
        state.state = _EJECTED
        state.rejoin_at = self._clock() + cooldown
        name = state.handle.name
        self._m_ejections.labels(name).inc()
        self._flight.record(
            "eject", replica=name, cause=cause,
            consecutive=state.consecutive_failures,
            cooldown_s=round(cooldown, 3),
        )
        self.trace_event(
            "eject", replica=name, cause=cause,
            consecutive=state.consecutive_failures,
            cooldown_s=round(cooldown, 3),
        )
        logger.info(
            f"router: ejected {name} ({cause}, "
            f"{state.consecutive_failures} consecutive, "
            f"cooldown {cooldown:.1f}s)"
        )

    def _has_routable(self, exclude: Sequence[str] = ()) -> bool:
        """Cheap existence check: is any un-excluded replica routable
        right now? (Used by hedging to avoid spending a retry-budget
        token on a lane whose pick would fail instantly — e.g. a
        1-replica fleet with a slow request every tail.)"""
        now = self._clock()
        with self._lock:
            for state in self._replicas.values():
                if state.handle.name in exclude:
                    continue
                if state.state == _LIVE:
                    return True
                if state.state == _EJECTED and now >= state.rejoin_at:
                    return True
                if state.state == _HALF_OPEN and not state.probe_inflight:
                    return True
        return False

    def _release_probe(self, name: str) -> None:
        """Free a half-open replica's probe slot without resolving the
        probe either way — for dispatch exits that say nothing about
        the replica's health (caller abandoned the stream, non-
        retryable caller error). No-op unless the replica is still
        half-open (success rejoins, retryable failure re-ejects)."""
        with self._lock:
            state = self._replicas.get(name)
            if state is not None and state.state == _HALF_OPEN:
                state.probe_inflight = False

    def _record_success(self, name: str) -> None:
        with self._lock:
            state = self._replicas.get(name)
            if state is None:
                return
            state.consecutive_failures = 0
            if state.state == _HALF_OPEN:
                state.state = _LIVE
                state.probe_inflight = False
                state.eject_count = 0  # probe succeeded: reset the ladder
                self._m_rejoins.labels(name).inc()
                self._flight.record("rejoin", replica=name, cause="probe_ok")
                self.trace_event("rejoin", replica=name, cause="probe_ok")
                logger.info(f"router: {name} rejoined after probe")

    # -- picking -----------------------------------------------------------

    def _health_of(self, state: _ReplicaState, now: float) -> dict:
        """Cached replica health (TTL ``policy.health_ttl_s``): pick
        runs per request, HTTP health is a network call. Strict ``<``
        so ``health_ttl_s=0`` means "always fresh" (tests with a
        frozen clock rely on this)."""
        if now - state.health_at < self.policy.health_ttl_s:
            return state.health_cache
        try:
            h = state.handle.health()
        except BaseException as exc:
            h = {"status": "unreachable", "error": str(exc)}
        with self._lock:
            state.health_cache = h
            state.health_at = now
        return h

    def _pick(
        self, prompt: Sequence[int], exclude: Sequence[str] = (),
        version: Optional[str] = None, version_soft: bool = True,
        exclude_version: Optional[str] = None,
    ) -> ReplicaHandle:
        """Choose the dispatch target: over routable candidates, score
        ``cache_w * cached_fraction - queue_w * queue_depth -
        burn_w * burn`` and take the max (ties: round-robin). Raises
        :class:`EngineUnavailable` when nothing is routable.

        ``version`` narrows the candidate set to replicas serving that
        model version. A SOFT constraint (rollout split assignment)
        falls back to the full routable set when nothing serves it —
        a dying canary sheds its traffic share, never a caller error.
        A HARD constraint (``X-Model-Version`` pin) raises: the
        retryable :class:`EngineUnavailable` when the version exists
        but nothing serving it is routable right now, ``ValueError``
        (the deterministic 422 class) when the version is unknown to
        the fleet. ``exclude_version`` is the soft inverse: prefer
        candidates NOT serving that version (how unassigned traffic
        stays off the canary while a split is open)."""
        t0 = time.perf_counter()
        now = self._clock()
        with self._lock:
            candidates: List[_ReplicaState] = []
            for state in self._replicas.values():
                if state.handle.name in exclude:
                    continue
                if state.state == _EJECTED and now >= state.rejoin_at:
                    state.state = _HALF_OPEN
                    self._flight.record(
                        "probe", replica=state.handle.name
                    )
                    self.trace_event("probe", replica=state.handle.name)
                if state.state == _LIVE:
                    candidates.append(state)
                elif state.state == _HALF_OPEN and not state.probe_inflight:
                    # exactly one in-flight probe through a half-open
                    # replica; it is picked ONLY when no live replica
                    # remains un-excluded, or as the probe trickle below
                    candidates.append(state)
            rr = self._rr
            self._rr += 1
        if version is not None:
            matched = [
                c for c in candidates
                if self._replica_version(c.handle) == version
            ]
            if matched:
                candidates = matched
            elif not version_soft:
                with self._lock:
                    known = {
                        self._replica_version(s.handle)
                        for s in self._replicas.values()
                    }
                known.discard(None)
                if self.live_version is not None:
                    known.add(self.live_version)
                if version in known:
                    raise EngineUnavailable(
                        f"no routable replica serves model version "
                        f"{version!r}",
                        reason="no_live_replicas", retry_after_s=1.0,
                    )
                raise ValueError(
                    f"unknown model version {version!r} — this fleet "
                    f"serves {sorted(known)}"
                )
            # soft + no match: fall through on the full candidate set
        elif exclude_version is not None:
            # the inverse constraint: unpinned traffic NOT assigned to
            # the split keeps off the split version's replicas (the
            # canary receives exactly its percent/tenant share, never
            # load-balancer spillover). Soft — when ONLY split-version
            # capacity is live (promote endgame, mass ejection) serving
            # beats refusing.
            kept = [
                c for c in candidates
                if self._replica_version(c.handle) != exclude_version
            ]
            if kept:
                candidates = kept
        if not candidates:
            raise EngineUnavailable(
                "no live replicas (all ejected, draining, or excluded)",
                reason="no_live_replicas",
                retry_after_s=self.policy.eject_cooldown_s,
            )
        half_open = [c for c in candidates if c.state == _HALF_OPEN]
        live = [c for c in candidates if c.state == _LIVE]
        # route the probe when a half-open replica is due one: the
        # probe IS how it rejoins — starving it keeps capacity ejected.
        # The claim is check-and-set UNDER the lock: two concurrent
        # picks must not both probe the same replica.
        chosen = None
        if half_open and (not live or rr % 8 == 0):
            with self._lock:
                for c in half_open:
                    if c.state == _HALF_OPEN and not c.probe_inflight:
                        c.probe_inflight = True
                        chosen = c
                        break
            if chosen is None and not live:
                raise EngineUnavailable(
                    "no live replicas (half-open probes already in "
                    "flight)", reason="no_live_replicas",
                    retry_after_s=1.0,
                )
        if chosen is None:
            # reachable only with live candidates: the no-live case
            # either claimed a probe above or raised
            pool = live
            prompt_len = max(1, len(prompt))
            best, best_score = None, None
            for i, state in enumerate(pool):
                h = self._health_of(state, now)
                if h.get("status") in ("draining", "unreachable"):
                    continue
                try:
                    cached = state.handle.cached_prefix_len(prompt)
                except BaseException:
                    cached = 0
                score = (
                    self.policy.cache_weight * (cached / prompt_len)
                    - self.policy.queue_weight * float(h.get("queue_depth", 0))
                    - self.policy.burn_weight * float(h.get("burn", 0.0))
                )
                if self.policy.latency_weight > 0.0:
                    # weighted least-request: a replica's rolling mean
                    # dispatch latency (seconds) sheds its share
                    samples = self._replica_latency.get(state.handle.name)
                    if samples is not None and len(samples):
                        score -= self.policy.latency_weight * samples.mean()
                if h.get("breaker_open"):
                    score -= 100.0
                if h.get("status") == "degraded":
                    score -= 10.0
                # deterministic round-robin tie-break
                if best_score is None or score > best_score + 1e-12:
                    best, best_score = state, score
                elif abs(score - best_score) <= 1e-12 and best is not None:
                    if (i + rr) % len(pool) < (pool.index(best) + rr) % len(pool):
                        best = state
            if best is None:
                # every candidate's own health said draining/unreachable
                raise EngineUnavailable(
                    "no routable replicas (all draining or unreachable)",
                    reason="no_live_replicas",
                    retry_after_s=1.0,
                )
            chosen = best
        self._h_pick_ms.observe((time.perf_counter() - t0) * 1e3)
        return chosen.handle

    # -- dispatch envelope -------------------------------------------------

    def _backoff_s(self, attempt: int, retry_after_s: float) -> float:
        base = min(
            self.policy.backoff_base_s * (2 ** (attempt - 1)),
            self.policy.backoff_max_s,
        )
        jitter = (
            self._rng.random() * self.policy.jitter_s
            if self.policy.jitter_s > 0 else 0.0
        )
        return max(base + jitter, retry_after_s)

    def _hedge_delay_s(self) -> float:
        if len(self._latency) < self.policy.hedge_warmup:
            return max(self.policy.hedge_min_s, 1.0)
        return max(
            self.policy.hedge_min_s,
            self._latency.percentile(self.policy.hedge_quantile),
        )

    # -- stitched routing timeline ----------------------------------------

    def _open_timeline(self, prompt_tokens: int):
        """``(rid, ctx, tracer)`` for one routed request: when
        stitching is on, a ``kind="route"`` recorder timeline keyed by
        the routing rid, parented into the caller's ambient trace
        scope (the transport's server span on a router app) — ``ctx``
        is its root context, the parent every pick/attempt span hangs
        from. The OPENING recorder is returned and threaded through to
        the close: a mid-request ``tracer`` swap must finish the
        timeline in the recorder it was opened in, never leak it live
        in the old one. ``(rid, None, None)`` when the plane is off."""
        tracer = self._tracer
        rid = telemetry.new_request_id()
        if tracer is None:
            return rid, None, None
        rid = tracer.new_request(
            "route", rid=rid, prompt_tokens=int(prompt_tokens),
        )
        return rid, tracer.trace_context(rid), tracer

    @staticmethod
    def _finish_timeline(tracer, rid: str) -> None:
        if tracer is not None:
            tracer.finish_request(rid)

    def _attempt_scope(self, t_ctx, span_id):
        """The child context one dispatch attempt propagates: the
        replica's server-side timeline (in-process engine, or a remote
        transport via the ``traceparent`` header) parents to the
        ATTEMPT span, so retried/hedged dispatches nest under the
        attempt that caused them, not interleaved under one parent."""
        if t_ctx is None:
            return nullcontext()
        return telemetry.trace_scope(telemetry.TraceContext(
            t_ctx.trace_id, span_id, t_ctx.sampled,
        ))

    def generate_stream(
        self, prompt: Sequence[int], *, max_new_tokens: Optional[int] = None,
    ) -> Iterator[List[int]]:
        """Stream token chunks with transparent mid-stream failover: a
        replica dying after K emitted tokens re-dispatches on a
        survivor and replays past the first K (engines decode
        deterministically for a fixed prompt, so the survivor's tokens
        are the same stream — chaos-tested for token parity). The
        caller sees one uninterrupted stream or, only once every
        attempt is exhausted, the last error."""
        if self._draining:
            raise EngineUnavailable(
                "router is draining", reason="draining",
            )
        self._deposit_budget()
        # resolved ONCE, on the caller's thread (the pin is thread-
        # local), so every retry of this request stays on one version
        version, version_soft, excl_version = self._resolve_route_version()
        rid, t_ctx, tracer = self._open_timeline(len(prompt))
        inner = self._stream_with_failover(
            rid, prompt, max_new_tokens=max_new_tokens, t_ctx=t_ctx,
            tracer=tracer, version=version, version_soft=version_soft,
            exclude_version=excl_version,
        )
        if t_ctx is None:
            return inner
        # _TracedStream (not a plain generator): the timeline must
        # close on EVERY exit, including the caller dropping the
        # iterator without ever pulling it (a generator's finally
        # never runs for a never-started body — a leaked live
        # timeline forever)
        return _TracedStream(tracer, rid, inner)

    def _stream_with_failover(self, rid, prompt, *, max_new_tokens,
                              dispatch=None, initial_exclude=(),
                              t_ctx=None, tracer=None,
                              version=None, version_soft=True,
                              exclude_version=None,
                              notify_rollout=True):
        """The retry envelope. ``dispatch(replica) -> chunk iterator``
        defaults to the replica's streaming primitive; the blocking
        path passes a single-yield wrapper over ``replica.generate``
        so both surfaces share one pick/retry/budget/ejection
        implementation. ``initial_exclude`` seeds the exclusion list
        with replicas a caller already saw fail (the hedge fallback) —
        the soft exclusion: if nothing else is routable, the pick
        fallback below relaxes it. ``t_ctx`` (the routing timeline's
        root context, when stitching is on) turns every decision into
        a recorded span: ``pick``, per-dispatch ``attempt`` (whose
        pre-minted span id is the child context the replica's own
        spans nest under), ``backoff`` — recorded into ``tracer``, the
        recorder captured at open (a mid-request swap must not split a
        timeline across recorders)."""
        emitted = 0          # tokens already yielded to the caller
        collected: List[int] = []   # the full live answer (shadow diff)
        attempt = 1
        tried: List[str] = list(initial_exclude)
        last_exc: Optional[BaseException] = None
        while attempt <= self.policy.max_attempts:
            t_pick0 = time.perf_counter()
            try:
                replica = self._pick(
                    prompt, exclude=tried,
                    version=version, version_soft=version_soft,
                    exclude_version=exclude_version,
                )
            except EngineUnavailable:
                # every distinct replica tried: allow a repeat pick
                # (the survivor set may have recovered) only if some
                # replica exists at all
                if not tried:
                    raise
                tried = tried[-1:]
                try:
                    replica = self._pick(
                        prompt, exclude=tried,
                        version=version, version_soft=version_soft,
                        exclude_version=exclude_version,
                    )
                except EngineUnavailable:
                    if last_exc is not None:
                        raise last_exc
                    raise
            name = replica.name
            if tracer is not None:
                tracer.record_span(
                    rid, "pick", t_pick0, time.perf_counter(),
                    replica=name, attempt=attempt,
                )
            if attempt == 1:
                rver = self._replica_version(replica)
                if rver is not None:
                    self._flight.record(
                        "route", rid=rid, replica=name, version=rver,
                    )
                else:
                    self._flight.record("route", rid=rid, replica=name)
            else:
                self._m_retries.labels(name).inc()
            attempt_span = (
                telemetry.new_span_id() if tracer is not None else None
            )
            t0 = time.perf_counter()
            skip = emitted
            replayed = emitted   # tokens this attempt must regenerate
            try:
                with _rid_scope(rid), self._attempt_scope(
                    t_ctx, attempt_span
                ):
                    # dispatch AND the first chunk pull run inside the
                    # scopes: HttpReplica builds its X-Request-ID /
                    # traceparent headers here (eager), and an
                    # in-process engine's lazy generator creates its
                    # request timeline on the first next() — both must
                    # see the attempt's child context so cross-hop
                    # spans nest under THIS attempt
                    source = iter(
                        dispatch(replica) if dispatch is not None
                        else replica.generate_stream(
                            prompt, max_new_tokens=max_new_tokens
                        )
                    )
                    head = list(itertools.islice(source, 1))
                for chunk in itertools.chain(head, source):
                    # replay-skip: a retry regenerates from the start;
                    # tokens the caller already holds are dropped here
                    if skip >= len(chunk):
                        skip -= len(chunk)
                        continue
                    out = chunk[skip:] if skip else chunk
                    skip = 0
                    emitted += len(out)
                    collected.extend(out)
                    yield out
                self._note_latency(name, time.perf_counter() - t0)
                self._record_success(name)
                self._m_routed.labels(name, "ok").inc()
                # the shadow hook: the complete live answer (replay-
                # skip makes `collected` whole across retries) is
                # offered to an operating RolloutController for
                # duplicate dispatch onto the canary. Strictly
                # free-rider — enqueue-only, exception-proof, after
                # the caller already has every token. A partial-answer
                # leg (disagg prefill) opts out: a 1-token leg result
                # must not diff against a full canary answer.
                if notify_rollout:
                    self._notify_rollout(
                        rid, name, prompt, max_new_tokens, collected,
                    )
                if tracer is not None:
                    tracer.record_span(
                        rid, "attempt", t0, time.perf_counter(),
                        span_id=attempt_span, replica=name,
                        attempt=attempt, outcome="ok", replayed=replayed,
                    )
                return
            except BaseException as exc:
                if tracer is not None:
                    outcome = (
                        "abandoned" if isinstance(exc, GeneratorExit)
                        else "error"
                    )
                    tracer.record_span(
                        rid, "attempt", t0, time.perf_counter(),
                        span_id=attempt_span, replica=name,
                        attempt=attempt, outcome=outcome,
                        error=type(exc).__name__, replayed=replayed,
                    )
                if not _retryable(exc):
                    # includes GeneratorExit (caller abandoned the
                    # stream): if this dispatch was a half-open probe,
                    # free the probe slot — a vanished consumer must
                    # not pin the replica half-open forever
                    self._release_probe(name)
                    self._m_routed.labels(name, "error").inc()
                    raise
                last_exc = exc
                self._record_failure(name, exc)
                tried.append(name)
                if (
                    attempt >= self.policy.max_attempts
                    or not self._spend_budget()
                ):
                    # the FINAL failure was not hidden from the caller:
                    # it counts as error, never also as retried_away
                    # (sum over outcomes == dispatches)
                    self._m_routed.labels(name, "error").inc()
                    raise last_exc
                self._m_routed.labels(name, "retried_away").inc()
                delay = self._backoff_s(
                    attempt, getattr(exc, "retry_after_s", 0.0)
                )
                self._flight.record(
                    "retry", rid=rid, replica=name, attempt=attempt,
                    reason=type(exc).__name__, backoff_s=round(delay, 4),
                    emitted=emitted,
                )
                t_back0 = time.perf_counter()
                self._sleep(delay)
                if tracer is not None:
                    tracer.record_span(
                        rid, "backoff", t_back0, time.perf_counter(),
                        attempt=attempt, delay_s=round(delay, 4),
                    )
                attempt += 1
        raise last_exc if last_exc is not None else EngineUnavailable(
            "retry attempts exhausted", reason="no_live_replicas",
        )

    def generate(
        self, prompt: Sequence[int], *, max_new_tokens: Optional[int] = None,
    ) -> List[int]:
        """Blocking single-prompt generate through the full robustness
        envelope: routed, retried, and (when ``policy.hedge``) hedged
        against tail latency — the second dispatch goes to a different
        replica after the observed ``hedge_quantile`` delay; first
        finished answer wins and the loser is cancelled.

        Dispatches via the replica's BLOCKING primitive (one event
        wait, not per-chunk queue hops): the 1-replica passthrough
        must cost ~a pick, not a streaming detour — the bench holds
        it under 2% p99 vs the direct engine."""
        if self.policy.hedge:
            return self._hedged_generate(prompt, max_new_tokens=max_new_tokens)
        if self._draining:
            raise EngineUnavailable(
                "router is draining", reason="draining",
            )
        self._deposit_budget()
        version, version_soft, excl_version = self._resolve_route_version()
        rid, t_ctx, tracer = self._open_timeline(len(prompt))
        try:
            return self._collect(self._stream_with_failover(
                rid, prompt, max_new_tokens=max_new_tokens,
                dispatch=lambda rep: iter(
                    [rep.generate(prompt, max_new_tokens=max_new_tokens)]
                ),
                t_ctx=t_ctx, tracer=tracer,
                version=version, version_soft=version_soft,
                exclude_version=excl_version,
            ))
        finally:
            self._finish_timeline(tracer, rid)

    @staticmethod
    def _collect(stream: Iterator[List[int]]) -> List[int]:
        out: List[int] = []
        for chunk in stream:
            out.extend(chunk)
        return out

    def _hedged_generate(self, prompt, *, max_new_tokens) -> List[int]:
        if self._draining:
            raise EngineUnavailable("router is draining", reason="draining")
        self._deposit_budget()
        rid, t_ctx, tracer = self._open_timeline(len(prompt))
        try:
            return self._hedged_inner(
                rid, t_ctx, tracer, prompt, max_new_tokens,
            )
        finally:
            # success, fallback, or error alike: the routing timeline
            # closes exactly once, exporting lanes + win/lose events —
            # in the recorder it was OPENED in, swap-proof
            self._finish_timeline(tracer, rid)

    def _hedged_inner(
        self, rid, t_ctx, tracer, prompt, max_new_tokens,
    ) -> List[int]:
        delay_s = self._hedge_delay_s()
        # resolved on the caller's thread (pin/split are thread-local /
        # counter-ordered): both hedge lanes dispatch the SAME version,
        # or deterministic decode could not guarantee identical tokens
        version, version_soft, excl_version = self._resolve_route_version()
        done = threading.Event()
        results: List = [None, None]   # per-lane (tokens | exception)
        lanes: List[Optional[str]] = [None, None]
        lane_spans: List[Optional[str]] = [None, None]
        lane_t0: List[Optional[float]] = [None, None]
        lane_recorded = [False, False]  # under winner_lock: span written
        winner_lock = threading.Lock()
        winner: List[Optional[int]] = [None]

        def record_lane(idx: int, outcome: str, end_s: float) -> None:
            """Write lane ``idx``'s span exactly once — from the lane's
            own finally, OR from the coordinator when the LOSER is
            still mid-decode at win time (the routing timeline closes
            with the response; a span recorded after that would be
            dropped, and the loser would vanish from the stitch)."""
            if tracer is None or lane_t0[idx] is None:
                return
            with winner_lock:
                if lane_recorded[idx]:
                    return
                lane_recorded[idx] = True
            tracer.record_span(
                rid, "hedge-lane", lane_t0[idx], end_s,
                span_id=lane_spans[idx], lane=idx,
                replica=lanes[idx] or "none", outcome=outcome,
            )

        # scopes are thread-local: capture the caller's and re-open
        # them inside each lane so deadlines/tenants/traces — and the
        # ambient per-request token cap, which decides OUTPUT LENGTH
        # and therefore token parity across dispatch paths — survive
        # the hop onto worker threads
        deadline = current_deadline_ms()
        tenant = current_tenant()
        priority = current_priority()
        token_cap = current_token_cap()
        trace_ctx = telemetry.current_trace_context()

        def start_lane(idx: int, exclude: List[str]) -> threading.Thread:
            """Pre-mint the lane's span id and start time BEFORE the
            thread spawns: the coordinator's loser-span write must
            never lose the race against a lane thread the scheduler
            hasn't run yet (lane_t0 unset → record_lane would no-op,
            the lane's own finally would then record into a finished
            timeline, and the loser would vanish from the stitch)."""
            if tracer is not None:
                lane_spans[idx] = telemetry.new_span_id()
            lane_t0[idx] = time.perf_counter()
            thread = threading.Thread(
                target=lane, args=(idx, exclude), daemon=True,
            )
            thread.start()
            return thread

        def lane(idx: int, exclude: List[str]) -> None:
            # each lane is its own recorded span; the lane's child
            # context (trace id + lane span id) is what the replica's
            # spans nest under, so win AND lose lanes stay separable
            # in the stitched timeline
            if tracer is not None:
                lane_ctx = telemetry.TraceContext(
                    t_ctx.trace_id, lane_spans[idx], t_ctx.sampled,
                )
            else:
                lane_ctx = trace_ctx
            try:
                with deadline_scope(deadline), tenant_scope(tenant), \
                        priority_scope(priority), \
                        token_cap_scope(token_cap), \
                        telemetry.trace_scope(lane_ctx), _rid_scope(rid):
                    replica = self._pick(
                        prompt, exclude=exclude,
                        version=version, version_soft=version_soft,
                        exclude_version=excl_version,
                    )
                    lanes[idx] = replica.name
                    t0 = time.perf_counter()
                    out: List[int] = []
                    for chunk in replica.generate_stream(
                        prompt, max_new_tokens=max_new_tokens
                    ):
                        # abandon on the WINNER flag alone: `done` is
                        # cleared by the coordinator's wait loop, so a
                        # done.is_set() condition here would race it
                        # and let the loser decode to completion —
                        # doubling device work on exactly the degraded
                        # fleet hedging protects. winner[0] is set
                        # once, never cleared. A failed sibling leaves
                        # it None, so a healthy lane never aborts for
                        # a sibling's error.
                        with winner_lock:
                            lost = (
                                winner[0] is not None and winner[0] != idx
                            )
                        if lost:
                            return  # lost: stop consuming (abandon)
                        out.extend(chunk)
                    self._note_latency(replica.name, time.perf_counter() - t0)
                    self._record_success(replica.name)
                    results[idx] = out
            except BaseException as exc:  # noqa: BLE001 — relayed below
                results[idx] = exc
                if lanes[idx] is not None and _retryable(exc):
                    self._record_failure(lanes[idx], exc)
            finally:
                record_lane(
                    idx,
                    (
                        "error"
                        if isinstance(results[idx], BaseException)
                        else "ok" if results[idx] is not None
                        else "abandoned"
                    ),
                    time.perf_counter(),
                )
                if lanes[idx] is not None:
                    # lost-and-abandoned or non-retryable exits say
                    # nothing about health: free the probe slot if this
                    # lane was a half-open probe (no-op otherwise)
                    self._release_probe(lanes[idx])
                with winner_lock:
                    if winner[0] is None and not isinstance(
                        results[idx], BaseException
                    ) and results[idx] is not None:
                        winner[0] = idx
                done.set()

        self._flight.record("route", rid=rid, replica="<hedged>")
        t_first = start_lane(0, [])
        t_first.join(timeout=delay_s)
        hedged = False
        exclude = [lanes[0]] if lanes[0] else []
        # a second routable replica must EXIST before a budget token is
        # spent: on a 1-replica fleet every slow request would otherwise
        # drain the shared bucket on lanes whose pick fails instantly,
        # starving genuine retries exactly when the fleet is thin
        if (
            t_first.is_alive()
            and self._has_routable(exclude=exclude)
            and self._spend_budget()
        ):
            hedged = True
            self._flight.record(
                "hedge", rid=rid, after_s=round(delay_s, 4),
                exclude=exclude,
            )
            t_second = start_lane(1, exclude)
        while True:
            # short-timeout wait: a lane's done.set() landing between
            # our clear() and wait() must not strand this loop
            done.wait(timeout=0.05)
            done.clear()
            with winner_lock:
                w = winner[0]
            if w is not None:
                break
            # a lane finished with an error; if the other lane is
            # still running, keep waiting for it
            alive = t_first.is_alive() or (
                hedged and t_second.is_alive()
            )
            if not alive:
                break
        with winner_lock:
            w = winner[0]
        if w is None:
            # every lane failed. A retryable failure falls back to the
            # sequential retry envelope (the hedge must not WEAKEN the
            # robustness contract — without this, one transient
            # Overloaded before the hedge delay would surface to the
            # caller that the non-hedged path retries transparently);
            # the fallback's extra dispatch draws a budget token like
            # any other retry. Ejection was already recorded per lane.
            errs = [r for r in results if isinstance(r, BaseException)]
            last = errs[-1] if errs else None
            retrying = (
                last is not None and _retryable(last) and self._spend_budget()
            )
            # account both lanes' dispatches (outcome disjointness:
            # every dispatch lands in exactly one bucket, hedged or
            # not): hidden by the fallback retry -> retried_away,
            # surfaced to the caller -> error
            for name in lanes:
                if name:
                    self._m_routed.labels(
                        name, "retried_away" if retrying else "error"
                    ).inc()
            if retrying:
                failed = [n for n in lanes if n]
                self._flight.record(
                    "retry", rid=rid, replica=",".join(failed) or "none",
                    attempt=1, reason=type(last).__name__,
                    backoff_s=0.0, emitted=0,
                )
                # the fallback must not immediately re-pick the lanes
                # that JUST failed (cache affinity still scores an
                # un-ejected primary highest) — seed the envelope's
                # exclusion with them
                return self._collect(self._stream_with_failover(
                    rid, prompt, max_new_tokens=max_new_tokens,
                    dispatch=lambda rep: iter(
                        [rep.generate(prompt, max_new_tokens=max_new_tokens)]
                    ),
                    initial_exclude=failed,
                    t_ctx=t_ctx, tracer=tracer,
                    version=version, version_soft=version_soft,
                    exclude_version=excl_version,
                ))
            if last is not None:
                raise last
            raise EngineUnavailable(
                "hedged dispatch produced no result", reason="hedge_failed",
            )
        win_name = lanes[w] or "none"
        self._m_routed.labels(win_name, "ok").inc()
        if hedged:
            self._m_hedges.labels(win_name, "win").inc()
            if tracer is not None:
                tracer.record_event(rid, "hedge_win", replica=win_name)
                # the loser may still be mid-decode (it abandons at its
                # next chunk): write its span NOW, before the timeline
                # closes with the response
                record_lane(1 - w, "abandoned", time.perf_counter())
            lose = lanes[1 - w]
            if lose:
                self._m_hedges.labels(lose, "lose").inc()
                # the loser's dispatch gets its own disjoint outcome
                # (it was neither ok nor an error — it was sacrificed)
                self._m_routed.labels(lose, "hedge_lose").inc()
                if tracer is not None:
                    tracer.record_event(rid, "hedge_lose", replica=lose)
        self._notify_rollout(
            rid, win_name, prompt, max_new_tokens, results[w],
        )
        return results[w]


class _TracedStream:
    """A streaming-response iterator that finishes its routing
    timeline exactly once, on EVERY exit: exhaustion, error,
    ``close()`` (client disconnect → the transport closes the SSE
    source), or garbage collection of a never-started iterator. Holds
    the recorder the timeline was OPENED in, so a mid-stream tracer
    swap on the router cannot leak the timeline live."""

    __slots__ = ("_tracer", "_rid", "_inner", "_finished")

    def __init__(self, tracer, rid, inner):
        self._tracer = tracer
        self._rid = rid
        self._inner = inner
        self._finished = False

    def _finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        self._tracer.finish_request(self._rid)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._inner)
        except BaseException:
            # StopIteration included: the stream is over either way
            self._finish()
            raise

    def close(self) -> None:
        try:
            self._inner.close()
        finally:
            self._finish()

    def __del__(self):
        try:
            # close (not just finish): the inner envelope's finally
            # must record its abandoned-attempt span BEFORE the
            # timeline closes, or a GC'd stream loses its last span
            self.close()
        except BaseException:
            pass  # interpreter teardown: never raise from __del__


class _RouterModel:
    """The minimal model-shaped object :class:`RouterApp` mounts on the
    transports (a router has no artifact of its own — its replicas
    do)."""

    def __init__(self, name: str):
        self.name = name
        self.artifact = object()  # "loaded": the fleet is the artifact


def make_router_app(router: FleetRouter, *, name: str = "fleet-router",
                    federate: bool = True, **kwargs):
    """The fleet router behind the standard serving surface.

    Returns a :class:`~unionml_tpu.serving.http.ServingApp` subclass
    instance whose predict paths dispatch through ``router`` — so BOTH
    transports (stdlib ``serve()``, :func:`~unionml_tpu.serving
    .fastapi.create_fastapi_app`) mount the front door unchanged: it
    speaks the same HTTP dialect as the replicas behind it — 429/503/
    504 fault mapping, ``traceparent``/``X-Tenant-ID``/``X-Request-ID``
    echo, ``X-Deadline-Ms`` scope, ``/metrics``, ``/debug/flight``,
    ``/debug/trace`` included. ``health``/``stats``/``drain`` default
    to the router's own (override via kwargs like any ServingApp).

    The router app is also the fleet's ONE observability plane
    (docs/observability.md "Fleet observability"):

    - ``GET /metrics`` federates every replica's exposition under a
      ``replica`` label next to the router's own series (one scrape
      target for the fleet; ``federate=False`` restores the local-only
      body). A failed replica scrape degrades to its last-seen-or-
      absent series — never an error.
    - ``GET /debug/trace?rid=<X-Request-ID>`` answers with ONE
      stitched end-to-end timeline: the router's pick/attempt/backoff/
      hedge spans plus the involved replicas' server-side spans,
      correctly parented across the hop (in-process replicas merge
      through the shared recorder; HTTP replicas are fetched).
    - ``GET /debug/flight`` merges replica flight rings time-ordered
      under a ``replica`` tag; ``GET /debug/fleet`` is the operator
      dashboard (per-replica health/breaker/drain, queue depth, cache
      blocks, burn, usage headroom, last scale decision).
    - ``GET /debug/slo`` / ``GET /debug/usage`` answer with
      fleet-aggregated views (router-side watchdog/ledger + merged
      per-replica reports).

    Subclassing (not transport changes) keeps the transports' single
    dispatch seam: everything the handlers know about routing an app
    applies verbatim to the router app.
    """
    # imported here, not at module top: http.py must stay importable
    # without router.py and vice versa (no cycle)
    from unionml_tpu.serving.http import ServingApp

    class _RouterServingApp(ServingApp):
        def __init__(self, router: FleetRouter, **kw):
            kw.setdefault("stats", router.stats)
            kw.setdefault("health", router.health)
            kw.setdefault("drain", router.drain)
            # the fleet-wide peek: a router app answers /debug/cache/
            # peek with the max over its replicas, so routers compose
            kw.setdefault("cache_peek", router.cached_prefix_len)
            # the app's telemetry sinks FOLLOW the router's: a router
            # built with an isolated tracer/flight/registry must not
            # silently serve /debug/trace?rid=, the fleet flight
            # merge, or /metrics from the process-global sinks its
            # routing timelines never land in
            kw.setdefault("registry", router._registry)
            kw.setdefault("flight", router._flight)
            if router.tracer is not None:
                kw.setdefault("tracer", router.tracer)
            super().__init__(_RouterModel(name), **kw)
            self.router = router
            self.federate = bool(federate)
            self._m_federation_failures = self.registry.counter(
                "unionml_router_federation_failures_total",
                "Replica observability fetches (metrics scrape, "
                "flight/trace pulls) that yielded NO data and degraded "
                "to absent series, by replica and surface (an "
                "HttpReplica serving its last-seen metrics body does "
                "not count — stale beats absent beats error; slo/usage "
                "pulls are uncounted, None legitimately means 'not "
                "wired' there).",
                ("replica", "surface"),
            )

        def setup_model(self):  # the fleet needs no artifact load
            return None

        # -- fleet observability plane --------------------------------

        # overall fan-out budget per fleet surface: slightly above one
        # HttpReplica obs_timeout_s, because fetches run CONCURRENTLY —
        # N wedged replicas must cost max(one timeout), never the sum
        # (a Prometheus scrape_timeout is ~10 s; a sequential walk of
        # three dead replicas would blow it and blind the operator to
        # the healthy fleet)
        FANOUT_TIMEOUT_S = 6.0

        def _fanout(self, items, fn) -> Dict[str, object]:
            """Fetch ``fn(handle)`` for every ``(name, handle)``: only
            ``remote`` handles (network fetches) go onto threads,
            concurrently under ONE overall deadline — in-process
            handles are lock-free local reads that must not pay a
            thread spawn per scrape. A replica that raises, or fails
            to answer inside the budget, maps to ``None`` (its daemon
            thread is abandoned, never joined past the deadline)."""
            if not items:
                return {}
            results: Dict[str, object] = {}
            threads = []
            for name, handle in items:
                if not getattr(handle, "remote", False):
                    try:
                        results[name] = fn(handle)
                    except BaseException:
                        results[name] = None
                    continue

                def run(name=name, handle=handle):
                    try:
                        results[name] = fn(handle)
                    except BaseException:
                        results[name] = None

                t = threading.Thread(target=run, daemon=True)
                t.start()
                threads.append(t)
            deadline = time.monotonic() + self.FANOUT_TIMEOUT_S
            for t in threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            return {name: results.get(name) for name, _ in items}

        def metrics_text(self) -> str:
            """The federated ``GET /metrics`` body: the router's own
            registry plus every replica's exposition under a
            ``replica`` label (bounded by fleet membership). Replicas
            sharing THIS app's registry are skipped — their series are
            already in the local body under their own instance
            labels."""
            local = super().metrics_text()
            if not self.federate:
                return local
            items = []
            for rep_name, handle in self.router.members().items():
                if (
                    type(handle).metrics_text
                    is ReplicaHandle.metrics_text
                ):
                    # the handle never wired a metrics source ("None =
                    # nothing to federate"): absent by design, not a
                    # failure — counting it would climb the failure
                    # counter forever with nothing failing
                    continue
                try:
                    reg = handle.metrics_registry()
                except BaseException:
                    reg = None
                if reg is not None and reg is self.registry:
                    continue  # already in the local exposition
                items.append((rep_name, handle))
            texts: Dict[str, str] = {}
            for rep_name, body in self._fanout(
                items, lambda h: h.metrics_text(),
            ).items():
                if body is None:
                    self._m_federation_failures.labels(
                        rep_name, "metrics",
                    ).inc()
                elif body:
                    texts[rep_name] = body
            if not texts:
                return local
            return telemetry.merge_expositions(local, texts)

        def debug_flight(self, n=None, kind=None, rid=None, tenant=None,
                         phase=None):
            """The fleet ``GET /debug/flight``: the router's own ring
            (route/retry/eject/scale_* events) merged with every
            replica's ring under a ``replica`` tag, time-ordered on
            WALL-ANCHORED ``t_ms`` (epoch milliseconds): each host's
            raw monotonic readings are rebased by its own
            ``wall_offset_ms`` anchor, because monotonic epochs are
            per-boot and a long-lived replica host would otherwise
            sort after everything the router recorded — and a
            ``?n=`` cut would then drop exactly the router's own
            events. Cross-host order is NTP-accurate; within one host
            it stays monotonic-exact. Replicas sharing this app's
            recorder are skipped (already merged). The merged
            response reports ``wall_offset_ms: 0`` — its events are
            pre-anchored."""
            local = super().debug_flight(n=None, kind=kind, rid=rid,
                                         tenant=tenant, phase=phase)
            # local + in-process rings share THIS host's clock: one
            # anchor rebases them all (copies — the ring's own dicts
            # must never be mutated)
            local_off = telemetry.wall_clock_offset_ms()
            events = [
                {**e, "t_ms": round(e.get("t_ms", 0.0) + local_off, 3)}
                for e in local["events"]
            ]
            replicas_merged = []
            items = []
            for rep_name, handle in self.router.members().items():
                try:
                    ring = handle.flight_recorder()
                except BaseException:
                    ring = None
                if ring is not None and ring is self._flight:
                    continue  # same ring: already in the local dump
                items.append((rep_name, handle))
            # ?n= may thin the FETCH only when no filter is active:
            # with a kind/rid/tenant filter, a per-replica newest-n cut
            # would run BEFORE the filter and silently drop matching
            # events that n newer non-matching ones displaced — filter
            # first, truncate the merged stream last, exactly like
            # FlightRecorder.dump
            fetch_n = n if (kind is None and rid is None
                            and tenant is None and phase is None) else None
            handles = dict(items)
            for rep_name, fetched in self._fanout(
                items, lambda h: h.flight_events(n=fetch_n),
            ).items():
                if fetched is None:
                    self._m_federation_failures.labels(
                        rep_name, "flight",
                    ).inc()
                    continue
                if not fetched:
                    continue
                replicas_merged.append(rep_name)
                anchored = getattr(handles[rep_name], "remote", False)
                for event in fetched:
                    if not isinstance(event, dict):
                        continue
                    tagged = dict(event)
                    if not anchored:
                        # in-process ring: same host, local anchor
                        # (remote events arrive pre-anchored by
                        # HttpReplica.flight_events)
                        tagged["t_ms"] = round(
                            tagged.get("t_ms", 0.0) + local_off, 3,
                        )
                    tagged.setdefault("replica", rep_name)
                    if kind is not None and tagged.get("kind") != kind:
                        continue
                    if rid is not None and not (
                        tagged.get("rid") == rid
                        or rid in tagged.get("rids", ())
                    ):
                        continue
                    if tenant is not None and (
                        tagged.get("tenant") != tenant
                    ):
                        continue
                    if phase is not None and not (
                        tagged.get("phase") == phase
                        or phase in tagged.get("phases", ())
                    ):
                        continue
                    events.append(tagged)
            events.sort(key=lambda e: e.get("t_ms", 0.0))
            if n is not None:
                n_int = int(n)
                events = events[-n_int:] if n_int > 0 else []
            return {**local, "wall_offset_ms": 0.0, "events": events,
                    "merged_replicas": sorted(replicas_merged)}

        def debug_trace(self, format: str = "chrome", rid=None,
                        trace=None):
            """``GET /debug/trace`` on the front door. Without
            ``rid``/``trace``: the local recorder export, unchanged.
            With them: ONE stitched end-to-end timeline for that
            request — the base stitching over this app's recorder
            (transport + router + any shared-recorder engine spans)
            plus the involved replicas' spans fetched through their
            handles (HTTP replicas answer their own
            ``/debug/trace?trace=``), deduplicated by span id and
            sorted on the wall-anchored axis."""
            if rid is None and trace is None:
                return super().debug_trace(format)
            doc, content_type = super().debug_trace(
                format, rid=rid, trace=trace,
            )
            trace_id = doc.get("trace_id")
            if not trace_id:
                return doc, content_type
            seen = {s.get("span_id") for s in doc["spans"]}
            items = []
            for rep_name, handle in self.router.members().items():
                try:
                    recorder = handle.trace_recorder()
                except BaseException:
                    recorder = None
                if recorder is not None and recorder is self._tracer:
                    continue  # shared recorder: already stitched
                items.append((rep_name, handle))
            for rep_name, fetched in self._fanout(
                items, lambda h: h.stitched_spans(trace_id),
            ).items():
                if fetched is None:
                    self._m_federation_failures.labels(
                        rep_name, "trace",
                    ).inc()
                    continue
                spans, events = fetched
                for span in spans:
                    if not isinstance(span, dict):
                        continue
                    if span.get("span_id") in seen:
                        continue
                    seen.add(span.get("span_id"))
                    tagged = dict(span)
                    tagged.setdefault("replica", rep_name)
                    doc["spans"].append(tagged)
                for event in events:
                    if isinstance(event, dict):
                        tagged = dict(event)
                        tagged.setdefault("replica", rep_name)
                        doc["events"].append(tagged)
            doc["spans"].sort(key=lambda s: s.get("start_unix_ms", 0.0))
            doc["events"].sort(key=lambda e: e.get("t_unix_ms", 0.0))
            return doc, content_type

        def debug_slo(self) -> dict:
            """The fleet ``GET /debug/slo``: the router-side
            watchdog's report (when the app was built with ``slo=``)
            plus every replica's own evaluation, with the fleet-level
            max fast/slow burn and the union of breached objectives on
            top. 422 only when NOTHING anywhere runs a watchdog."""
            router_report = (
                self._slo.evaluate() if self._slo is not None else None
            )
            replicas: Dict[str, Optional[dict]] = dict(self._fanout(
                list(self.router.members().items()),
                lambda h: h.slo_report(),
            ))
            reports = [r for r in replicas.values() if r]
            if router_report is not None:
                reports.append(router_report)
            if not reports:
                raise ValueError(
                    "no SLO watchdog anywhere in the fleet — build the "
                    "router app with slo=SloWatchdog([...]) or the "
                    "replicas with per-replica watchdogs"
                )
            burn = {"fast": 0.0, "slow": 0.0}
            breached: List[str] = []
            for report in reports:
                for obj in report.get("objectives", ()):
                    for window in ("fast", "slow"):
                        rate = (
                            obj.get("windows", {})
                            .get(window, {})
                            .get("burn_rate", 0.0)
                        )
                        burn[window] = max(burn[window], float(rate))
                breached.extend(report.get("breached", ()))
            return {
                "fleet": {
                    "burn": burn,
                    "breached": sorted(set(breached)),
                },
                "router": router_report,
                "replicas": replicas,
            }

        def debug_goodput(self) -> dict:
            """The fleet ``GET /debug/goodput``: every replica's
            serving goodput report plus fleet-merged ratios recomputed
            on the SUMMED slot-step ledgers (a big engine's padding
            must outweigh a small one's — averaging per-replica ratios
            would weight them equally). 422 only when no replica runs
            the perf plane."""
            replicas: Dict[str, Optional[dict]] = dict(self._fanout(
                list(self.router.members().items()),
                lambda h: h.goodput_report(),
            ))
            reports = [r for r in replicas.values() if r]
            if not reports:
                raise ValueError(
                    "no serving goodput plane anywhere in the fleet — "
                    "build the replica engines with DecodeEngine("
                    "perf=True) (the default while introspect=True)"
                )
            passes: Dict[str, int] = {}
            slot_steps: Dict[str, float] = {}
            occupied = tokens = tokens_per_s = 0.0
            reasons: List[str] = []
            for report in reports:
                for kind, count in report.get("passes", {}).items():
                    passes[kind] = passes.get(kind, 0) + int(count)
                for kind, steps in report.get("slot_steps", {}).items():
                    slot_steps[kind] = (
                        slot_steps.get(kind, 0.0) + float(steps)
                    )
                occupied += float(report.get("occupied_slot_steps", 0))
                tokens += float(report.get("tokens", 0))
                tokens_per_s += float(report.get("tokens_per_s", 0.0))
                reasons.extend(
                    (report.get("watchdog") or {}).get("reasons", ())
                )
            idle = slot_steps.get("idle", 0.0)
            dispatched = sum(slot_steps.values()) - idle
            total = dispatched + idle
            return {
                "fleet": {
                    "replicas": len(reports),
                    "passes": passes,
                    "slot_steps": {
                        k: round(v, 3) for k, v in slot_steps.items()
                    },
                    "occupied_slot_steps": round(occupied, 3),
                    "goodput_ratio": (
                        round(occupied / total, 6) if total else 0.0
                    ),
                    "occupancy_ratio": (
                        round(occupied / dispatched, 6)
                        if dispatched else 0.0
                    ),
                    "tokens": int(tokens),
                    "tokens_per_s": round(tokens_per_s, 3),
                    "regressed": sorted(set(reasons)),
                },
                "replicas": replicas,
            }

        def debug_usage(self) -> dict:
            """The fleet ``GET /debug/usage``: per-replica ledger
            reports plus merged per-tenant vectors summed across the
            fleet (numeric fields add; distinct ledgers only — N
            replicas sharing ONE ledger merge once). 422 only when no
            ledger exists anywhere."""
            router_report = (
                self._usage.report() if self._usage is not None else None
            )
            replicas: Dict[str, Optional[dict]] = {}
            seen_ledgers = {id(self._usage)} if (
                self._usage is not None
            ) else set()
            merge_from: List[dict] = []
            if router_report is not None:
                merge_from.append(router_report)
            # in-process ledger-identity dedup happens BEFORE the
            # fan-out: N replicas sharing one ledger fetch it once
            items = []
            for rep_name, handle in self.router.members().items():
                try:
                    ledger = handle.usage_ledger()
                except BaseException:
                    ledger = None
                if ledger is not None:
                    if id(ledger) in seen_ledgers:
                        replicas[rep_name] = {"shared_ledger": True}
                        continue
                    seen_ledgers.add(id(ledger))
                items.append((rep_name, handle))
            fetched = self._fanout(items, lambda h: h.usage_report())
            for rep_name, _ in items:
                report = fetched.get(rep_name)
                replicas[rep_name] = report
                if report:
                    merge_from.append(report)
            if not merge_from:
                raise ValueError(
                    "no usage ledger anywhere in the fleet — build the "
                    "replicas with DecodeEngine(usage=True) or the "
                    "router app with usage=UsageLedger()"
                )
            tenants: Dict[str, dict] = {}
            totals = {"device_seconds": 0.0, "flops": 0.0, "tokens": 0}
            cap_steps = used_weighted = 0.0
            savings = 0
            for report in merge_from:
                for tenant_name, vector in report.get(
                    "tenants", {}
                ).items():
                    acc = tenants.setdefault(tenant_name, {})
                    for field, value in vector.items():
                        if isinstance(value, (int, float)):
                            acc[field] = acc.get(field, 0) + value
                for field in totals:
                    totals[field] += report.get("totals", {}).get(
                        field, 0
                    )
                savings += report.get("cache_savings_tokens", 0)
                capacity = report.get("capacity", {})
                steps = float(capacity.get("slot_steps", 0.0))
                cap_steps += steps
                used_weighted += steps * sum(
                    capacity.get("per_tenant", {}).values()
                )
            headroom = (
                max(0.0, 1.0 - used_weighted / cap_steps)
                if cap_steps > 0 else 1.0
            )
            return {
                "fleet": {
                    "tenants": tenants,
                    "totals": totals,
                    "cache_savings_tokens": savings,
                    "capacity": {
                        "slot_steps": cap_steps,
                        "headroom": round(headroom, 4),
                    },
                    "merged_reports": len(merge_from),
                },
                "router": router_report,
                "replicas": replicas,
            }

        def debug_fleet(self) -> dict:
            """``GET /debug/fleet``: the operator dashboard —
            :meth:`FleetRouter.fleet_report` (per-replica health/
            breaker/drain state, queue depth, cache blocks, burn,
            retry budget) plus the operating autoscaler's view (usage
            headroom, burn windows, last scale decision + reason) when
            one is attached."""
            return self.router.fleet_report()

        def debug_rollout(self) -> dict:
            """``GET /debug/rollout``: the rollout operator surface —
            stage, canary pool, split spec, shadow diff stats, streaks
            and decision history (docs/robustness.md "Rollouts &
            rollback"). 422 when no controller operates this router."""
            rollout = self.router.rollout
            if rollout is None:
                raise ValueError(
                    "no rollout controller operates this router — "
                    "construct a RolloutController(router, ...) first"
                )
            return rollout.dashboard()

        def predict(self, payload: dict):
            if self._draining:
                raise EngineUnavailable(
                    "router app is draining", reason="draining",
                )
            rows = _prompt_rows(payload)
            # the payload-contract token cap (422 on garbage), passed
            # explicitly so HttpReplica forwards it across a further hop
            cap = validate_token_cap(payload.get("max_new_tokens"))
            if len(rows) == 1:
                return [self.router.generate(rows[0], max_new_tokens=cap)]
            # multi-prompt: dispatch rows CONCURRENTLY so the replica
            # engines continuous-batch them, instead of serializing N
            # full generations behind one another (each worker re-opens
            # the caller's thread-local scopes, hedge-lane style)
            deadline = current_deadline_ms()
            tenant = current_tenant()
            priority = current_priority()
            # the version pin is thread-local like the rest: a pinned
            # multi-row predict must pin EVERY row's dispatch
            version_pin = current_model_version()
            trace_ctx = telemetry.current_trace_context()
            results: List = [None] * len(rows)

            def run(i: int) -> None:
                try:
                    with deadline_scope(deadline), tenant_scope(tenant), \
                            priority_scope(priority), \
                            model_version_scope(version_pin), \
                            telemetry.trace_scope(trace_ctx):
                        results[i] = self.router.generate(
                            rows[i], max_new_tokens=cap,
                        )
                except BaseException as exc:  # relayed in submit order
                    results[i] = exc

            threads = [
                threading.Thread(target=run, args=(i,), daemon=True)
                for i in range(len(rows))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for r in results:
                if isinstance(r, BaseException):
                    raise r
            return results

        def predict_stream(self, payload: dict):
            if self._draining:
                raise EngineUnavailable(
                    "router app is draining", reason="draining",
                )
            rows = _prompt_rows(payload)
            if len(rows) != 1:
                raise ValueError(
                    f"streaming serves one prompt per request, "
                    f"got {len(rows)}"
                )
            return self.router.generate_stream(
                rows[0],
                max_new_tokens=validate_token_cap(
                    payload.get("max_new_tokens")
                ),
            )

        def resume(self):
            super().resume()
            self.router.resume()

    return _RouterServingApp(router, **kwargs)


def _prompt_rows(payload: dict) -> List[List[int]]:
    """Token-prompt rows from a ``{"features": ...}`` payload (one
    prompt, or a list of prompts). The router tier speaks token ids —
    feature readers live on the replicas."""
    features = payload.get("features")
    if not features:
        raise ValueError(
            "router predict requires non-empty 'features' (a token-id "
            "prompt or a list of prompts)"
        )
    rows = (
        features
        if isinstance(features[0], (list, tuple)) else [features]
    )
    out = []
    for row in rows:
        if not row:
            raise ValueError("empty prompt")
        out.append([int(t) for t in row])
    return out
