"""Fault-tolerance primitives for the serving layer.

The reference delegates every failure to Flyte retries and has no
overload story at all (SURVEY.md §5.3); this module is the serving-side
analog of the elastic trainer's ``fault_hook`` seam
(:mod:`unionml_tpu.elastic`): a small, dependency-free vocabulary that
makes every failure mode **typed**, **deterministic**, and therefore
**CPU-testable**:

- typed serving errors the transports map to HTTP statuses —
  :class:`Overloaded` (429 + ``Retry-After``),
  :class:`EngineUnavailable` (503: circuit breaker open or draining),
  :class:`DeadlineExceeded` (504: the request's deadline expired before
  the device ran it);
- a request-deadline **propagation channel**
  (:func:`deadline_scope` / :func:`current_deadline_ms`): the HTTP
  layer parses ``X-Deadline-Ms`` and opens a scope around the
  predictor call, so the engine and batcher pick the deadline up
  without every predictor wrapper in between having to thread a
  kwarg through its signature (submissions happen on the request's
  own thread in both transports);
- :class:`FaultInjector` — the chaos harness. Deterministic, seeded
  injection points the engine and batcher ``fire()`` at their
  structurally interesting moments (program dispatch, harvest,
  dequeue), so tier-1 tests can reproduce a device-program crash, a
  slow harvest, a queue stall, or an OOM-shaped XLA error on CPU,
  byte-for-byte the same on every run (docs/robustness.md).
"""

from __future__ import annotations

import math
import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, Optional

__all__ = [
    "DeadlineExceeded",
    "EngineUnavailable",
    "FaultInjector",
    "INJECTION_POINTS",
    "Overloaded",
    "current_deadline_ms",
    "deadline_scope",
    "http_fault_response",
    "parse_deadline_header",
    "xla_oom_error",
]

# the injection points the engine/batcher fire, for discoverability
# (arming an unknown point is an error — a typo'd chaos test would
# otherwise silently inject nothing and pass vacuously)
INJECTION_POINTS = (
    "engine.prefill",    # before a prefill/admission program dispatch
    "engine.dispatch",   # before a decode-chunk program dispatch
    "engine.harvest",    # before a readback is materialized
    "engine.dequeue",    # before the dispatcher pops the next request
    "batcher.predict",   # before the batcher's shared device call
)


class Overloaded(RuntimeError):
    """Admission refused: the bounded queue is full. Retry later.

    ``retry_after_s`` is the transport's ``Retry-After`` hint."""

    def __init__(self, message: str, *, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class EngineUnavailable(RuntimeError):
    """Admission refused fast: circuit breaker open, or draining.

    ``reason`` is ``"breaker_open"`` or ``"draining"``;
    ``retry_after_s`` is the transport's ``Retry-After`` hint (the
    breaker's remaining cooldown, or a drain-poll interval)."""

    def __init__(
        self, message: str, *, reason: str = "unavailable",
        retry_after_s: float = 1.0,
    ):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s


class DeadlineExceeded(TimeoutError):
    """The request's deadline expired before the device served it.

    Raised at **dequeue**, not submit: an expired request is shed before
    it consumes prefill, which is the whole point of deadlines under
    overload (finishing it would burn device time on an answer the
    client already stopped waiting for)."""

    def __init__(self, message: str, *, deadline_ms: Optional[float] = None):
        super().__init__(message)
        self.deadline_ms = deadline_ms


def http_fault_response(exc: BaseException):
    """Map a typed serving error to ``(status, extra_headers)`` — the
    ONE definition of the HTTP contract, consumed by both transports so
    they cannot drift: :class:`Overloaded` → 429 + ``Retry-After``,
    :class:`EngineUnavailable` → 503 + ``Retry-After``,
    :class:`DeadlineExceeded` → 504. Returns ``None`` for anything
    else. ``Retry-After`` is whole seconds >= 1 (the header is
    integer-valued)."""
    if isinstance(exc, (Overloaded, EngineUnavailable)):
        retry = str(max(1, math.ceil(getattr(exc, "retry_after_s", 1.0))))
        return (
            429 if isinstance(exc, Overloaded) else 503,
            {"Retry-After": retry},
        )
    if isinstance(exc, DeadlineExceeded):
        return 504, {}
    return None


def xla_oom_error(nbytes: int = 8 << 30) -> RuntimeError:
    """An OOM-shaped device error for chaos tests: the message matches
    what benchmarks/serve_latency.py's OOM detection looks for in real
    XLA ``RESOURCE_EXHAUSTED`` failures, so harness-injected OOMs walk
    the same string-matching paths production errors do."""
    return RuntimeError(
        f"RESOURCE_EXHAUSTED: Out of memory allocating {nbytes} bytes "
        "(injected by unionml_tpu.serving.faults.FaultInjector)"
    )


# --------------------------------------------------------------------- #
# deadline propagation (thread-local: submissions run on the request's
# own thread in both the stdlib and FastAPI-sync transports)
# --------------------------------------------------------------------- #

_deadline_tls = threading.local()


@contextmanager
def deadline_scope(deadline_ms: Optional[float]) -> Iterator[None]:
    """Expose ``deadline_ms`` to engine/batcher submissions made on this
    thread (``None`` is a no-op scope). Scopes nest; the innermost wins."""
    if deadline_ms is not None and deadline_ms <= 0:
        raise ValueError(f"deadline_ms must be positive, got {deadline_ms}")
    prev = getattr(_deadline_tls, "deadline_ms", None)
    _deadline_tls.deadline_ms = deadline_ms
    try:
        yield
    finally:
        _deadline_tls.deadline_ms = prev


def current_deadline_ms() -> Optional[float]:
    """The innermost :func:`deadline_scope` value on this thread."""
    return getattr(_deadline_tls, "deadline_ms", None)


def parse_deadline_header(raw: Optional[str]) -> Optional[float]:
    """Parse an ``X-Deadline-Ms`` header value — the ONE parser both
    HTTP transports use, so the header contract cannot drift between
    them. ``None`` (absent header) passes through; anything that is not
    a finite positive number raises ``ValueError`` (NaN/inf would
    silently disable shedding — a malformed deadline must be a 422, not
    a no-deadline)."""
    if raw is None:
        return None
    try:
        value = float(raw)
    except ValueError:
        value = math.nan
    if not math.isfinite(value) or value <= 0:
        raise ValueError(
            "X-Deadline-Ms must be a positive number of milliseconds, "
            f"got {raw!r}"
        )
    return value


# --------------------------------------------------------------------- #
# chaos injection
# --------------------------------------------------------------------- #


class _Plan:
    __slots__ = ("after", "count", "exc", "delay_s", "injected")

    def __init__(self, after: int, count: int,
                 exc: Optional[BaseException], delay_s: float):
        self.after = after      # absolute hit index the plan starts at
        self.count = count      # injections before the plan disarms
        self.exc = exc
        self.delay_s = delay_s
        self.injected = 0


class FaultInjector:
    """Deterministic, seeded chaos-injection points.

    The engine and batcher call :meth:`fire` at fixed structural points
    (:data:`INJECTION_POINTS`); a test :meth:`arm`\\ s a point to raise
    an exception and/or sleep on the *nth subsequent* firing. All
    scheduling is hit-count based — never wall-clock or RNG draws at
    fire time — so a chaos test replays identically on every run and
    every host. (``seed`` is reserved for future probabilistic plans;
    the deterministic counters are what tier-1 relies on.)

    Thread-safe: fire sites live on the engine's dispatcher/harvester
    threads while tests arm from the main thread.

    Example::

        fi = FaultInjector()
        engine = DecodeEngine(module, ..., fault_injector=fi)
        ...                       # traffic running
        fi.arm("engine.dispatch", exc=faults.xla_oom_error())
        # the NEXT decode-chunk dispatch raises the OOM-shaped error;
        # the engine fails only the poisoned batch and recovers.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {}
        self._injections: Dict[str, int] = {}
        self._plans: Dict[str, _Plan] = {}

    def arm(
        self,
        point: str,
        *,
        nth: int = 1,
        count: int = 1,
        exc: Optional[BaseException] = None,
        delay_s: float = 0.0,
    ) -> None:
        """Schedule an injection at ``point``: the ``nth`` firing after
        this call (1 = the very next) injects, and the following
        ``count - 1`` firings do too. ``exc`` raises (after sleeping
        ``delay_s`` — both together model a slow-then-dead program);
        ``delay_s`` alone models a stall (slow harvest, queue stall)."""
        if point not in INJECTION_POINTS:
            raise ValueError(
                f"unknown injection point {point!r} — known points: "
                f"{INJECTION_POINTS}"
            )
        if nth < 1 or count < 1:
            raise ValueError("nth and count must be >= 1")
        if exc is None and delay_s <= 0.0:
            raise ValueError("arm() needs an exc and/or a positive delay_s")
        with self._lock:
            self._plans[point] = _Plan(
                after=self._hits.get(point, 0) + nth - 1,
                count=count, exc=exc, delay_s=delay_s,
            )

    def disarm(self, point: Optional[str] = None) -> None:
        """Cancel the plan at ``point`` (all points when ``None``)."""
        with self._lock:
            if point is None:
                self._plans.clear()
            else:
                self._plans.pop(point, None)

    def fire(self, point: str) -> None:
        """An injection site: count the hit, inject if a plan says so.
        Cheap and lock-short when nothing is armed (the production
        no-injector path never even gets here — the engine guards on
        ``fault_injector is None``)."""
        with self._lock:
            self._hits[point] = self._hits.get(point, 0) + 1
            plan = self._plans.get(point)
            if plan is None or self._hits[point] <= plan.after:
                return
            plan.injected += 1
            self._injections[point] = self._injections.get(point, 0) + 1
            if plan.injected >= plan.count:
                del self._plans[point]
            exc, delay_s = plan.exc, plan.delay_s
        if delay_s > 0.0:
            time.sleep(delay_s)
        if exc is not None:
            raise exc

    def fired(self, point: str) -> int:
        """Hits observed at ``point`` (armed or not)."""
        with self._lock:
            return self._hits.get(point, 0)

    def injected(self, point: str) -> int:
        """Injections actually performed at ``point``."""
        with self._lock:
            return self._injections.get(point, 0)
