"""Automatic prefix KV-cache: radix-tree prompt reuse over a host store.

Real LLM traffic repeats itself: system prompts, few-shot templates, and
chat histories give most requests a long common prefix, yet a decode
engine that re-prefills every admitted prompt from row 0 pays the full
O(prompt) prefill each time. This module is the cross-request reuse
layer — RadixAttention (SGLang, Zheng et al. 2024) / vLLM automatic
prefix caching (Kwon et al., SOSP 2023) restructured for this
framework's host/device split:

- :class:`RadixPrefixCache` — a thread-safe radix tree keyed on fixed-size
  **token blocks** (``block_size`` tokens per node, the vLLM block
  granularity: every distinct block length would otherwise compile its
  own XLA splice/prefill executable). Each node owns the host-RAM copy
  of its block's KV rows (the per-layer tuple-of-tuples cache tree,
  ``[1, block, ...]`` numpy leaves — rank-generic, so bf16 KV buffers
  and int8-cache scale planes ride along unchanged).
- a **byte-budgeted host block store**: inserts charge each block's
  ``nbytes`` against ``max_bytes`` and evict least-recently-used leaf
  blocks to fit. Eviction is leaf-first (a parent's rows stay valid
  without its children) and skips blocks that are **pinned** (e.g. a
  ``system_prefix``) or **leased** by an in-flight admission — an entry
  referenced by a running prefill can never be freed under it.
- :class:`PrefixLease` — the in-use pin handle :meth:`RadixPrefixCache.match`
  returns: it holds refcounts on every matched node until the engine has
  spliced the rows to device (and inserted any new suffix blocks), then
  releases exactly once.

The device side lives in :class:`unionml_tpu.serving.engine.DecodeEngine`:
on admission it walks this tree for the longest cached prefix, splices
the matched block rows into the slot's fresh cache (host→device, one
compiled ``[1, block]`` splice program), prefills only the uncovered
suffix, and on prefill completion copies the prompt's new full blocks
back here (device→host, async). This module itself never imports jax —
it is a pure host-memory structure, safe to unit-test and reuse anywhere.

Telemetry (the PR-1 registry; all series carry a per-instance ``cache``
label):

- ``unionml_prefix_cache_hits_total`` / ``_partial_hits_total`` /
  ``_misses_total`` — lookup outcomes (full = every cacheable block of
  the prompt matched),
- ``unionml_prefix_cache_prefill_tokens_saved_total`` — prompt tokens
  whose prefill was skipped because their KV came from the cache,
- ``unionml_prefix_cache_bytes`` / ``_entries`` — store gauges,
- ``unionml_prefix_cache_evictions_total`` /
  ``_inserted_blocks_total`` / ``_insert_rejected_blocks_total``,
- ``unionml_prefix_cache_lookup_ms`` / ``_insert_ms`` — latency
  histograms.
"""

from __future__ import annotations

import base64
import heapq
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from unionml_tpu import telemetry

__all__ = [
    "RadixPrefixCache",
    "PrefixLease",
    "decode_entries",
    "encode_entries",
    "tree_nbytes",
]


def tree_nbytes(rows: Any) -> int:
    """Total bytes of one block's KV tree (tuple-of-tuples of arrays)."""
    total = 0
    for layer in rows:
        for buf in layer:
            total += int(np.asarray(buf).nbytes)
    return total


# --------------------------------------------------------------------- #
# wire codecs for export entries (the cross-host KV handoff)
# --------------------------------------------------------------------- #


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype name back to numpy, covering the accelerator
    extension dtypes (``bfloat16`` etc.) numpy itself cannot name —
    they come from ``ml_dtypes``, which jax always ships."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _encode_buf(buf: Any) -> dict:
    a = np.ascontiguousarray(np.asarray(buf))
    return {
        "dtype": str(a.dtype),
        "shape": list(a.shape),
        "data": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def _decode_buf(spec: dict) -> np.ndarray:
    raw = base64.b64decode(spec["data"])
    a = np.frombuffer(raw, dtype=_np_dtype(spec["dtype"]))
    # .copy(): frombuffer views are read-only; the store's arrays must
    # be ordinary owned host buffers like every other inserted block
    return a.reshape([int(d) for d in spec["shape"]]).copy()


def encode_entries(entries: Sequence[dict]) -> List[dict]:
    """JSON-safe form of :meth:`RadixPrefixCache.export_request` /
    :meth:`~RadixPrefixCache.export_hot` entries — the wire format of
    ``POST /debug/kv/export`` ↔ ``/debug/kv/import`` (docs/serving.md
    "Disaggregated serving"). Each KV buffer ships as dtype + shape +
    base64 bytes; rank-generic, so bf16 KV buffers and int8-cache
    scale planes ride along unchanged."""
    out: List[dict] = []
    for entry in entries:
        out.append({
            "tokens": [int(t) for t in np.asarray(entry["tokens"]).ravel()],
            "first_block": int(entry["first_block"]),
            "rows": [
                [_encode_buf(buf) for buf in layer]
                for layer in entry["rows"]
            ],
        })
    return out


def decode_entries(payload: Sequence[dict]) -> List[dict]:
    """Inverse of :func:`encode_entries`: rebuild importable entries
    (numpy rows) from the wire form. Raises ``ValueError`` on a
    malformed body — the transports map it to 422."""
    out: List[dict] = []
    try:
        for entry in payload:
            out.append({
                "tokens": np.asarray(
                    [int(t) for t in entry["tokens"]], np.int32,
                ),
                "first_block": int(entry["first_block"]),
                "rows": tuple(
                    tuple(_decode_buf(buf) for buf in layer)
                    for layer in entry["rows"]
                ),
            })
    except (KeyError, TypeError, AttributeError) as exc:
        raise ValueError(f"malformed KV entry payload: {exc!r}") from exc
    return out


class _Node:
    """One cached block: ``block_size`` tokens' KV rows plus tree links.

    ``refcount`` counts live :class:`PrefixLease` holders (in-flight
    admissions reading or extending this path); ``pinned`` marks blocks
    under a registered pin sequence (``system_prefix``). Either makes
    the node unevictable."""

    __slots__ = (
        "key", "rows", "nbytes", "children", "parent", "refcount",
        "pinned", "last_used", "depth",
    )

    def __init__(self, key: bytes, rows: Any, nbytes: int,
                 parent: Optional["_Node"], depth: int):
        self.key = key
        self.rows = rows
        self.nbytes = nbytes
        self.children: Dict[bytes, "_Node"] = {}
        self.parent = parent
        self.refcount = 0
        self.pinned = False
        self.last_used = 0
        self.depth = depth  # block index (root = -1)


class PrefixLease:
    """In-use pin over the matched path; release exactly once.

    ``rows`` is the list of matched blocks' host KV trees in prompt
    order (``n_blocks`` entries, each covering ``block_size`` tokens).
    The engine may consume fewer than all of them; the lease still pins
    the whole path so a follow-up :meth:`RadixPrefixCache.insert` of suffix
    blocks finds its ancestors alive."""

    __slots__ = ("_cache", "_nodes", "rows", "n_blocks", "n_tokens")

    def __init__(self, cache: "RadixPrefixCache", nodes: List[_Node]):
        self._cache = cache
        self._nodes = nodes
        self.rows = [n.rows for n in nodes]
        self.n_blocks = len(nodes)
        self.n_tokens = len(nodes) * cache.block_size

    def release(self) -> None:
        """Drop the in-use pins (idempotent AND race-safe — an engine
        error path and the normal insert path may both reach here; the
        node-list swap happens under the cache lock so the refcounts
        can only ever be decremented once)."""
        with self._cache._lock:
            nodes, self._nodes = self._nodes, []
            for node in nodes:
                node.refcount -= 1


class RadixPrefixCache:
    """Radix tree of prompt-prefix KV blocks in a byte-budgeted host store.

    Args:
        block_size: tokens per tree node. The device side compiles one
            splice and one suffix-prefill executable per ``[1,
            block_size]`` shape, so this quantizes both the key space
            and the reusable match length (a match is usable in
            ``block_size`` steps). 16 matches the vLLM default; larger
            blocks cut per-admission dispatches, smaller ones waste
            fewer tokens on the rounded-down tail.
        max_bytes: host-RAM budget for stored KV rows. Inserting past it
            evicts least-recently-used unpinned, unleased leaf blocks;
            when nothing is evictable the incoming blocks are dropped
            (counted in ``insert_rejected_blocks``), never the in-use
            ones.
        registry: explicit :class:`~unionml_tpu.telemetry
            .MetricsRegistry`; defaults to the process-global one so
            ``GET /metrics`` picks the cache up automatically.
    """

    def __init__(
        self,
        *,
        block_size: int = 16,
        max_bytes: int = 256 << 20,
        registry: Optional[telemetry.MetricsRegistry] = None,
    ):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        self.block_size = int(block_size)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._root = _Node(b"", None, 0, None, -1)
        self._bytes = 0
        self._entries = 0
        self._clock = 0  # monotone LRU stamp (under the lock)
        self._pinned_seqs: List[np.ndarray] = []
        self._registry = (
            registry if registry is not None else telemetry.get_registry()
        )
        self.instance = telemetry.instance_label("prefix_cache")
        self._build_instruments()

    # ------------------------------------------------------------------ #
    # telemetry
    # ------------------------------------------------------------------ #

    def _build_instruments(self) -> None:
        R, lbl = self._registry, {"cache": self.instance}

        def counter(name, help):
            return R.counter(name, help, ("cache",)).labels(**lbl)

        def hist(name, help):
            return R.histogram(name, help, ("cache",)).labels(**lbl)

        self._m_hits = counter(
            "unionml_prefix_cache_hits_total",
            "Lookups where every cacheable block of the prompt matched.",
        )
        self._m_partial = counter(
            "unionml_prefix_cache_partial_hits_total",
            "Lookups matching some but not all cacheable prompt blocks.",
        )
        self._m_misses = counter(
            "unionml_prefix_cache_misses_total",
            "Lookups matching no cached block.",
        )
        self._m_saved = counter(
            "unionml_prefix_cache_prefill_tokens_saved_total",
            "Prompt tokens whose prefill was skipped via cached KV rows.",
        )
        self._m_evictions = counter(
            "unionml_prefix_cache_evictions_total",
            "Blocks evicted to fit the byte budget.",
        )
        self._m_inserted = counter(
            "unionml_prefix_cache_inserted_blocks_total",
            "Blocks attached to the tree.",
        )
        self._m_rejected = counter(
            "unionml_prefix_cache_insert_rejected_blocks_total",
            "Blocks dropped because the budget had no evictable room.",
        )
        self._g_bytes = R.gauge(
            "unionml_prefix_cache_bytes",
            "Host bytes held by stored KV blocks.", ("cache",),
        ).labels(**lbl)
        self._g_entries = R.gauge(
            "unionml_prefix_cache_entries",
            "Blocks resident in the radix tree.", ("cache",),
        ).labels(**lbl)
        self._h_lookup = hist(
            "unionml_prefix_cache_lookup_ms", "match() wall time.",
        )
        self._h_insert = hist(
            "unionml_prefix_cache_insert_ms", "insert() wall time.",
        )

    # ------------------------------------------------------------------ #
    # lookup / insert
    # ------------------------------------------------------------------ #

    def _block_key(self, tokens: np.ndarray, i: int) -> bytes:
        b = self.block_size
        return tokens[i * b:(i + 1) * b].tobytes()

    def match(self, tokens: Sequence[int]) -> PrefixLease:
        """Longest cached block-prefix of ``tokens``; pins the path.

        Returns a :class:`PrefixLease` (possibly empty). The caller MUST
        :meth:`~PrefixLease.release` it — leased blocks are immune to
        eviction until then. Counts the lookup as a hit (all
        ``len(tokens) // block_size`` cacheable blocks matched), partial
        hit, or miss; a prompt with ZERO cacheable blocks (shorter than
        one block) is not counted at all — the cache was never
        applicable, and a miss there would read as mis-sizing."""
        t0 = time.perf_counter()
        tokens = np.ascontiguousarray(tokens, np.int32).ravel()
        cacheable = len(tokens) // self.block_size
        nodes: List[_Node] = []
        with self._lock:
            self._clock += 1
            node = self._root
            for i in range(cacheable):
                child = node.children.get(self._block_key(tokens, i))
                if child is None:
                    break
                child.refcount += 1
                child.last_used = self._clock
                nodes.append(child)
                node = child
        if cacheable == 0:
            pass
        elif not nodes:
            self._m_misses.inc()
        elif len(nodes) == cacheable:
            self._m_hits.inc()
        else:
            self._m_partial.inc()
        self._h_lookup.observe((time.perf_counter() - t0) * 1e3)
        return PrefixLease(self, nodes)

    def lease(self, tokens: Sequence[int]) -> PrefixLease:
        """Pin the longest cached block-prefix of ``tokens`` WITHOUT
        counting a lookup — eviction-target pinning for the preemptive
        scheduler (docs/robustness.md "Preemption & fairness"): right
        after a preempted stream's blocks are inserted, the engine
        leases the path so LRU pressure cannot reclaim them before the
        resume admission splices them back (which would silently turn
        a lossless pointer-swap resume into a recompute). Bumps the
        LRU clock (the blocks ARE hot) but records no hit/miss — this
        is bookkeeping, not serving traffic, and it must not distort
        the hit-rate telemetry the way router :meth:`peek` must not."""
        tokens = np.ascontiguousarray(tokens, np.int32).ravel()
        nodes: List[_Node] = []
        with self._lock:
            self._clock += 1
            node = self._root
            for i in range(len(tokens) // self.block_size):
                child = node.children.get(self._block_key(tokens, i))
                if child is None:
                    break
                child.refcount += 1
                child.last_used = self._clock
                nodes.append(child)
                node = child
        return PrefixLease(self, nodes)

    def peek(self, tokens: Sequence[int]) -> int:
        """Longest cached block-prefix of ``tokens``, in TOKENS — a
        read-only probe for routing-affinity decisions (the fleet
        router scores replicas by who holds the longest prefix,
        SGLang-style). Unlike :meth:`match` it takes no lease, bumps no
        LRU clock, and records no hit/miss counters: a router peeking
        N replicas per request must not distort the per-replica cache
        telemetry or pin paths it never admits against."""
        tokens = np.ascontiguousarray(tokens, np.int32).ravel()
        cacheable = len(tokens) // self.block_size
        matched = 0
        with self._lock:
            node = self._root
            for i in range(cacheable):
                child = node.children.get(self._block_key(tokens, i))
                if child is None:
                    break
                matched += 1
                node = child
        return matched * self.block_size

    def insert(
        self,
        tokens: Sequence[int],
        first_block: int,
        blocks: Sequence[Any],
    ) -> int:
        """Attach ``blocks`` (host KV trees for token blocks
        ``[first_block, first_block + len(blocks))`` of ``tokens``) to
        the tree; returns how many were newly attached.

        Blocks whose node already exists are skipped (their arrays are
        dropped — concurrent identical admissions race benignly). Blocks
        whose ancestors are missing (evicted mid-flight with no lease
        held) are dropped too: a child's rows are meaningless without
        the prefix path above them. Each attach charges the byte budget
        and evicts LRU unpinned/unleased leaves to fit; when nothing
        more is evictable the remaining blocks are rejected."""
        t0 = time.perf_counter()
        tokens = np.ascontiguousarray(tokens, np.int32).ravel()
        attached = rejected = evicted = 0
        with self._lock:
            self._clock += 1
            # the walked/attached chain is refcount-protected for the
            # duration of the call: a mid-insert eviction pass must not
            # pick a block we just attached (or are attaching under) as
            # its LRU victim — that would detach the chain while we keep
            # charging the budget for nodes no longer reachable
            path: List[_Node] = []
            # the eviction heap is seeded by ONE tree walk for the whole
            # insert and reused across the block loop (nodes that turn
            # unevictable are re-validated at pop): at steady state —
            # store at budget, the normal LRU condition — a rescan per
            # block would make each admission's insert O(blocks×entries)
            # under the lock the dispatcher's match() waits on
            heap: Optional[List[Tuple[int, int, _Node]]] = None

            def step(n: _Node) -> _Node:
                n.refcount += 1
                path.append(n)
                return n

            node = self._root
            ok = True
            for i in range(first_block):
                node = node.children.get(self._block_key(tokens, i))
                if node is None:
                    ok = False
                    break
                step(node)
            if ok:
                for j, rows in enumerate(blocks):
                    i = first_block + j
                    key = self._block_key(tokens, i)
                    child = node.children.get(key)
                    if child is not None:
                        child.last_used = self._clock
                        node = step(child)
                        continue
                    nbytes = tree_nbytes(rows)
                    n, heap = self._evict_locked(
                        self.max_bytes - nbytes, heap
                    )
                    evicted += n
                    if self._bytes + nbytes > self.max_bytes:
                        rejected += len(blocks) - j
                        break
                    child = _Node(key, rows, nbytes, node, i)
                    child.last_used = self._clock
                    child.pinned = self._under_pin(tokens, i)
                    node.children[key] = child
                    node = step(child)
                    self._bytes += nbytes
                    self._entries += 1
                    attached += 1
            else:
                rejected += len(blocks)
            for n in path:
                n.refcount -= 1
            self._sync_gauges_locked()
        if attached:
            self._m_inserted.inc(attached)
        if rejected:
            self._m_rejected.inc(rejected)
        if evicted:
            self._m_evictions.inc(evicted)
        self._h_insert.observe((time.perf_counter() - t0) * 1e3)
        return attached

    def record_saved_tokens(self, n: int) -> None:
        """Credit ``n`` prompt tokens whose prefill the caller skipped
        by splicing cached rows (the engine calls this per admission)."""
        if n > 0:
            self._m_saved.inc(n)

    # ------------------------------------------------------------------ #
    # fleet warming (export / import)
    # ------------------------------------------------------------------ #

    def export_hot(self, *, max_blocks: int = 64) -> List[dict]:
        """The fleet's warm-join donor path (Mooncake/SGLang cache-aware
        lineage, docs/robustness.md "Autoscaling & self-healing"): the
        hottest ``<= max_blocks`` cached blocks as host-RAM entries a
        peer cache can :meth:`import_blocks`, so a freshly provisioned
        replica's first requests hit warm prefixes instead of
        recomputing them.

        Selection is most-recently-used first with **ancestor closure**
        (a child's rows are meaningless without the prefix path above
        it, so a hot deep node pulls its whole path in). Each entry is
        ``{"tokens", "first_block", "rows"}`` — exactly one
        :meth:`insert` call — emitted parent-before-child so the
        importer attaches ancestors first. The selected path is held
        under a :class:`PrefixLease` (refcount pinning) while entries
        are built, so a concurrent insert's eviction pass can never
        reclaim a block out from under the export; ``rows`` reference
        the store's own arrays (host KV rows are write-once — both
        caches splice from them read-only), so exporting costs
        pointers, not a copy of the bytes. Like :meth:`peek`, no
        hit/miss counters move: warming is bookkeeping, not serving
        traffic."""
        if max_blocks < 1:
            return []
        with self._lock:
            nodes: List[_Node] = []
            stack = list(self._root.children.values())
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                nodes.append(node)
            nodes.sort(key=lambda n: n.last_used, reverse=True)
            selected: List[_Node] = []
            chosen = set()
            for node in nodes:
                if id(node) in chosen:
                    continue
                # ancestor closure: walk up to the first already-chosen
                # (or root) ancestor; the whole chain ships or none
                chain: List[_Node] = []
                cur = node
                while cur is not None and cur.parent is not None and (
                    id(cur) not in chosen
                ):
                    chain.append(cur)
                    cur = cur.parent
                if len(selected) + len(chain) > max_blocks:
                    continue  # try a shallower hot node
                for n in chain:
                    chosen.add(id(n))
                    selected.append(n)
                if len(selected) >= max_blocks:
                    break
            # parent-before-child order == ascending depth
            selected.sort(key=lambda n: n.depth)
            for n in selected:
                n.refcount += 1
            lease = PrefixLease(self, selected)
        try:
            entries: List[dict] = []
            for node in selected:
                path_keys: List[bytes] = []
                cur: Optional[_Node] = node
                while cur is not None and cur.parent is not None:
                    path_keys.append(cur.key)
                    cur = cur.parent
                tokens = np.concatenate([
                    np.frombuffer(k, np.int32) for k in reversed(path_keys)
                ]) if path_keys else np.zeros((0,), np.int32)
                entries.append({
                    "tokens": tokens,
                    "first_block": node.depth,
                    "rows": node.rows,
                })
            return entries
        finally:
            lease.release()

    def export_request(self, tokens: Sequence[int]) -> List[dict]:
        """Export the cached blocks covering ONE specific prompt — the
        disaggregated-serving KV handoff donor path (docs/serving.md
        "Disaggregated serving"), the per-request twin of the fleet-
        warming :meth:`export_hot`: a prefill engine finalizes a
        request's KV into this store, then the router (or the remote
        ``POST /debug/kv/export`` handler) pulls exactly that request's
        blocks to splice on a decode engine in another process.

        Walks the longest cached block-prefix of ``tokens`` and emits
        one ``{"tokens", "first_block", "rows"}`` entry per matched
        block, parent-before-child (each is exactly one
        :meth:`insert`/:meth:`import_blocks` call on the importer).
        The path is pinned under a :class:`PrefixLease` while entries
        are built — a concurrent insert's eviction pass can never
        reclaim a block mid-export — and ``rows`` reference the
        store's own write-once arrays, so a same-process export costs
        pointers, not copies (the wire serialization, when the import
        crosses a host boundary, is the transport's business). Like
        :meth:`peek`/:meth:`lease`, no hit/miss counters move: the
        handoff is bookkeeping, not a cache lookup."""
        tokens = np.ascontiguousarray(tokens, np.int32).ravel()
        lease = self.lease(tokens)
        try:
            blk = self.block_size
            entries: List[dict] = []
            for i, rows in enumerate(lease.rows):
                entries.append({
                    "tokens": tokens[: (i + 1) * blk].copy(),
                    "first_block": i,
                    "rows": rows,
                })
            return entries
        finally:
            lease.release()

    def import_blocks(self, entries: Sequence[dict]) -> int:
        """Attach :meth:`export_hot` entries from a donor cache (the
        warm-join import path); returns how many blocks were newly
        attached. Each entry rides the normal :meth:`insert` budget/
        eviction machinery — an importer at its byte budget keeps its
        own LRU discipline, and entries whose ancestors were rejected
        drop harmlessly."""
        attached = 0
        for entry in entries:
            attached += self.insert(
                entry["tokens"], int(entry["first_block"]), [entry["rows"]],
            )
        return attached

    # ------------------------------------------------------------------ #
    # pinning / eviction
    # ------------------------------------------------------------------ #

    def pin(self, tokens: Sequence[int]) -> None:
        """Mark every block under ``tokens`` never-evictable — present
        AND future (blocks inserted later along this path are pinned at
        attach time). The ``system_prefix`` back-compat path."""
        tokens = np.ascontiguousarray(tokens, np.int32).ravel()
        if tokens.size == 0:
            return
        with self._lock:
            self._pinned_seqs.append(tokens)
            node = self._root
            for i in range(len(tokens) // self.block_size):
                node = node.children.get(self._block_key(tokens, i))
                if node is None:
                    break
                node.pinned = True

    def _under_pin(self, tokens: np.ndarray, i: int) -> bool:
        """Is block ``i`` of ``tokens`` covered by a pinned sequence?
        (lock held)"""
        end = (i + 1) * self.block_size
        for seq in self._pinned_seqs:
            if seq.size >= end and np.array_equal(seq[:end], tokens[:end]):
                return True
        return False

    @staticmethod
    def _evictable(node: _Node) -> bool:
        return not node.children and not node.pinned and node.refcount == 0

    def _evict_locked(
        self,
        budget: int,
        heap: Optional[List[Tuple[int, int, _Node]]] = None,
    ) -> Tuple[int, Optional[List[Tuple[int, int, _Node]]]]:
        """Evict LRU evictable leaves until ``self._bytes <= budget``;
        returns ``(evicted, heap)``. ONE tree walk seeds a min-heap of
        evictable leaves keyed on recency — built lazily and returned so
        a multi-block ``insert()`` reuses it across its whole loop;
        parents are pushed as their last child goes and every pop is
        re-validated, so the total cost per insert is O(entries +
        evictions·log entries), not a rescan per victim (the lock is
        held, and the dispatcher's ``match()`` waits on it). (lock
        held)"""
        if self._bytes <= budget:
            return 0, heap
        if heap is None:
            heap = []
            stack = [self._root]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if node is not self._root and self._evictable(node):
                    heapq.heappush(heap, (node.last_used, id(node), node))
        evicted = 0
        while self._bytes > budget and heap:
            _, _, victim = heapq.heappop(heap)
            if victim.parent is None or not self._evictable(victim):
                continue  # detached or re-shielded since pushed
            parent = victim.parent
            del parent.children[victim.key]
            self._bytes -= victim.nbytes
            self._entries -= 1
            victim.parent = None
            victim.rows = None
            evicted += 1
            if parent is not self._root and self._evictable(parent):
                heapq.heappush(heap, (parent.last_used, id(parent), parent))
        return evicted, heap

    # ------------------------------------------------------------------ #
    # maintenance / views
    # ------------------------------------------------------------------ #

    def clear(self) -> None:
        """Drop every stored block (pinned included — cached KV belongs
        to ONE weight binding; the engine clears on a params swap). Pin
        registrations survive, so re-inserted prefix blocks re-pin."""
        with self._lock:
            self._root.children.clear()
            self._bytes = 0
            self._entries = 0
            self._sync_gauges_locked()

    def _sync_gauges_locked(self) -> None:
        self._g_bytes.set(self._bytes)
        self._g_entries.set(self._entries)

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    @property
    def entries(self) -> int:
        with self._lock:
            return self._entries

    def stats(self) -> dict:
        """The ``prefix_cache`` section of ``DecodeEngine.stats()`` /
        ``GET /stats`` — a thin view over this instance's registry
        series (the same numbers ``GET /metrics`` exposes)."""
        hits = int(self._m_hits.value)
        partial = int(self._m_partial.value)
        misses = int(self._m_misses.value)
        lookups = hits + partial + misses
        out = {
            "block_size": self.block_size,
            "max_bytes": self.max_bytes,
            "bytes": self.bytes,
            "entries": self.entries,
            "hits": hits,
            "partial_hits": partial,
            "misses": misses,
            "hit_rate": round((hits + partial) / max(1, lookups), 3),
            "prefill_tokens_saved": int(self._m_saved.value),
            "evictions": int(self._m_evictions.value),
            "inserted_blocks": int(self._m_inserted.value),
            "insert_rejected_blocks": int(self._m_rejected.value),
        }
        for name, h in (
            ("lookup_ms", self._h_lookup), ("insert_ms", self._h_insert),
        ):
            summary = h.summary()
            if summary:
                out[name] = summary
        return out

    def reset_stats(self) -> None:
        """Zero the flow counters/histograms (benchmarks call this
        between phases); the store gauges re-sync to live contents."""
        for m in (
            self._m_hits, self._m_partial, self._m_misses, self._m_saved,
            self._m_evictions, self._m_inserted, self._m_rejected,
            self._h_lookup, self._h_insert,
        ):
            m.reset()
        with self._lock:
            self._sync_gauges_locked()
