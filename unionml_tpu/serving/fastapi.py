"""FastAPI adapter: mount the serving surface on a user-supplied app.

Route-for-route parity with reference unionml/fastapi.py:15-70, delegating
all behavior to :class:`unionml_tpu.serving.http.ServingApp` so the stdlib
and FastAPI transports cannot drift. FastAPI/pydantic are optional — when
absent (e.g. minimal TPU VM images), use ``unionml_tpu.serving.create_app``
or pass ``app=None`` to ``Model.serve``.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from unionml_tpu import telemetry
from unionml_tpu.serving.faults import (
    DeadlineExceeded,
    EngineUnavailable,
    Overloaded,
    deadline_scope,
    http_fault_response,
    parse_deadline_header,
)
from unionml_tpu.serving.http import ServingApp
from unionml_tpu.serving.scheduler import (
    model_version_scope,
    priority_scope,
    validate_model_version,
    validate_priority,
)
from unionml_tpu.serving.usage import tenant_scope, validate_tenant


def serving_app(
    model,
    app: Any = None,
    *,
    remote: bool = False,
    app_version: Optional[str] = None,
    model_version: str = "latest",
    batch: bool = False,
    core: Optional[ServingApp] = None,
    **batcher_kwargs,
):
    """Mount ``/``, ``/predict``, ``/health`` (reference: fastapi.py:15-70).

    With ``app=None`` returns the dependency-free :class:`ServingApp`;
    otherwise ``app`` must be a FastAPI instance.

    ``core``: mount a pre-built :class:`ServingApp` (or subclass —
    e.g. the fleet router's :func:`~unionml_tpu.serving.router
    .make_router_app` front door) instead of constructing one from
    ``model``; every route below dispatches through the core, so the
    router speaks FastAPI exactly as it speaks the stdlib transport.
    ``model`` and the construction kwargs are ignored when given.
    """
    if core is None:
        core = ServingApp(
            model,
            remote=remote,
            app_version=app_version,
            model_version=model_version,
            batch=batch,
            **batcher_kwargs,
        )
    if app is None:
        return core

    try:
        from fastapi import FastAPI, HTTPException, Request, Response  # gated optional import
        from fastapi.responses import HTMLResponse
    except ImportError as exc:
        raise ImportError(
            "fastapi is not installed. Pass app=None (or use "
            "unionml_tpu.serving.create_app) for the dependency-free HTTP "
            "server, or install fastapi+uvicorn."
        ) from exc

    if not isinstance(app, FastAPI):
        raise TypeError(f"app must be a FastAPI instance, got {type(app)}")

    @app.on_event("startup")
    async def setup_model():  # reference: fastapi.py:22-34
        core.setup_model()

    @app.get("/", response_class=HTMLResponse)
    def root():  # reference: fastapi.py:36-48
        return core.root()

    def _parse_deadline(request) -> Optional[float]:
        try:  # the shared parser: the two transports cannot drift
            return parse_deadline_header(request.headers.get("x-deadline-ms"))
        except ValueError as exc:
            raise HTTPException(status_code=422, detail=str(exc))

    def _parse_tenant(request) -> str:
        try:  # the shared validator: same 422 contract as stdlib
            return validate_tenant(request.headers.get("x-tenant-id"))
        except ValueError as exc:
            raise HTTPException(status_code=422, detail=str(exc))

    def _parse_priority(request) -> str:
        try:  # the shared validator: same 422 contract as stdlib
            return validate_priority(request.headers.get("x-priority"))
        except ValueError as exc:
            raise HTTPException(status_code=422, detail=str(exc))

    def _parse_model_version(request) -> str:
        try:  # the shared validator: same 422 contract as stdlib
            return validate_model_version(
                request.headers.get("x-model-version")
            )
        except ValueError as exc:
            raise HTTPException(status_code=422, detail=str(exc))

    def _fault_http(
        exc: Exception, rid: Optional[str] = None
    ) -> "HTTPException":
        """The faults.http_fault_response contract (429/503 +
        Retry-After, 504) — same mapping the stdlib transport sends.
        ``rid`` rides the error headers: Starlette discards the
        route's Response on an HTTPException, so without this the
        middleware would stamp a DIFFERENT X-Request-ID than the one
        the recorded timeline is keyed by — and /debug/trace?rid=
        would 422 for exactly the failed requests an operator wants
        to trace."""
        status, extra = http_fault_response(exc)
        headers = dict(extra or {})
        if rid is not None:
            headers["X-Request-ID"] = rid
        return HTTPException(
            status_code=status, detail=str(exc), headers=headers or None
        )

    def _invalid_http(
        exc: Exception, rid: Optional[str] = None
    ) -> "HTTPException":
        """422 with the timeline rid riding the headers (same reason
        as :func:`_fault_http`)."""
        return HTTPException(
            status_code=422, detail=str(exc),
            headers={"X-Request-ID": rid} if rid is not None else None,
        )

    _FAULTS = (Overloaded, EngineUnavailable, DeadlineExceeded)

    # sync `def` (here and on /predict/stream), not `async def`: FastAPI
    # then runs the blocking predictor call in the threadpool instead of
    # freezing the event loop — and the thread-local deadline_scope AND
    # trace_scope stay on the thread that performs the engine/batcher
    # submission (the middleware's thread is the event loop's, so the
    # traceparent must be parsed HERE, like the deadline header).
    @app.post("/predict")
    def predict(payload: dict, request: Request, response: Response):
        # reference: fastapi.py:50-64. The route mints the request id
        # itself and keys the recorded timeline by it (the middleware
        # only fills X-Request-ID when a route didn't), so
        # /debug/trace?rid=<X-Request-ID> resolves the id the client
        # actually received — same contract as the stdlib transport.
        rid = telemetry.new_request_id()
        response.headers["X-Request-ID"] = rid
        try:
            with core.traced_request(
                "/predict", request.headers.get("traceparent"), rid=rid,
            ) as ctx:
                response.headers["traceparent"] = (
                    telemetry.format_traceparent(ctx)
                )
                # tenant/priority parsed HERE like the deadline: the
                # scopes must live on the threadpool thread that
                # submits to the engine/batcher, not the event loop's
                with tenant_scope(_parse_tenant(request)):
                    with priority_scope(_parse_priority(request)), \
                            model_version_scope(
                                _parse_model_version(request)):
                        with deadline_scope(_parse_deadline(request)):
                            return core.predict(payload)
        except _FAULTS as exc:
            raise _fault_http(exc, rid)
        except HTTPException:
            raise  # header-parse 422s: already shaped
        except (ValueError, KeyError, TypeError) as exc:
            raise _invalid_http(exc, rid)

    # the body's blocking first-chunk pull — queue + prefill, ~120 ms at
    # 8B, up to submit_timeout on a wedged engine — also runs in the
    # threadpool. The wire framing comes from the shared
    # core.predict_stream_events, so the two transports cannot drift.
    @app.post("/predict/stream")
    def predict_stream(payload: dict, request: Request):  # SSE token streaming
        from fastapi.responses import StreamingResponse

        # the open/finish seam, not the context manager: the response
        # body outlives this handler frame, and the server span must
        # cover the WHOLE stream (parity with the stdlib transport),
        # so the timeline closes when the frame generator does. The
        # trace_scope itself only needs to cover the validating
        # first-chunk pull — that is where the engine timeline is
        # created and parented.
        rid = telemetry.new_request_id()
        ctx, finish = core.open_traced_request(
            "/predict/stream", request.headers.get("traceparent"),
            rid=rid,
        )
        try:
            with telemetry.trace_scope(ctx):
                with tenant_scope(_parse_tenant(request)):
                    with priority_scope(_parse_priority(request)), \
                            model_version_scope(
                                _parse_model_version(request)):
                        with deadline_scope(_parse_deadline(request)):
                            frames = core.predict_stream_events(payload)
        except _FAULTS as exc:
            finish()
            raise _fault_http(exc, rid)
        except HTTPException:
            finish()
            raise  # header-parse 422s: already shaped
        except (ValueError, KeyError, TypeError) as exc:
            finish()
            raise _invalid_http(exc, rid)
        except BaseException:
            finish()
            raise

        def stream_then_finish():
            try:
                yield from frames
            finally:
                finish()

        return StreamingResponse(
            stream_then_finish(), media_type="text/event-stream",
            headers={
                "traceparent": telemetry.format_traceparent(ctx),
                "X-Request-ID": rid,
            },
        )

    @app.get("/health")
    async def health():  # reference: fastapi.py:66-70
        from fastapi.responses import JSONResponse

        h = core.health()
        # same not-ready => 503 contract as the stdlib transport
        return JSONResponse(h, status_code=core.health_status(h))

    @app.get("/stats")
    async def stats():  # no reference counterpart: latency attribution
        return core.stats()

    @app.get("/metrics")
    async def metrics():  # Prometheus scrape (same body as the stdlib app)
        from fastapi.responses import Response

        return Response(
            core.metrics_text(),
            media_type=telemetry.EXPOSITION_CONTENT_TYPE,
        )

    # debug/introspection surface (docs/observability.md) — same
    # ServingApp methods as the stdlib transport, so the two cannot
    # drift. Sync `def` for the profiler capture: it blocks for the
    # capture window and must not freeze the event loop.
    @app.post("/debug/profile")
    def debug_profile(seconds: float = 2.0):
        from unionml_tpu.introspection import ProfileInProgress

        try:
            return core.debug_profile(seconds)
        except ProfileInProgress as exc:
            raise HTTPException(status_code=409, detail=str(exc))
        except (ValueError, TypeError) as exc:
            raise HTTPException(status_code=422, detail=str(exc))

    @app.get("/debug/memory")
    async def debug_memory():
        return core.debug_memory()

    @app.get("/debug/flight")
    async def debug_flight(
        n: Optional[int] = None,
        kind: Optional[str] = None,
        rid: Optional[str] = None,
        tenant: Optional[str] = None,
        phase: Optional[str] = None,
    ):
        return core.debug_flight(
            n=n, kind=kind, rid=rid, tenant=tenant, phase=phase,
        )

    # the cross-host KV handoff surface (docs/serving.md
    # "Disaggregated serving") — same ServingApp methods as the
    # stdlib transport. Sync `def`: the export may briefly poll for
    # in-flight inserts and must not freeze the event loop.
    @app.post("/debug/kv/export")
    def debug_kv_export(payload: dict):
        try:
            return core.debug_kv_export(payload.get("prompt") or [])
        except (ValueError, TypeError) as exc:
            raise HTTPException(status_code=422, detail=str(exc))

    @app.post("/debug/kv/import")
    def debug_kv_import(payload: dict):
        try:
            return core.debug_kv_import(payload.get("entries"))
        except (ValueError, TypeError) as exc:
            raise HTTPException(status_code=422, detail=str(exc))

    @app.get("/debug/usage")
    async def debug_usage():
        try:
            return core.debug_usage()
        except ValueError as exc:
            raise HTTPException(status_code=422, detail=str(exc))

    @app.get("/debug/cache/peek")
    async def debug_cache_peek(prompt: str = ""):
        try:
            return core.debug_cache_peek(prompt)
        except (ValueError, TypeError) as exc:
            raise HTTPException(status_code=422, detail=str(exc))

    @app.get("/debug/trace")
    async def debug_trace(
        format: str = "chrome",
        rid: Optional[str] = None,
        trace: Optional[str] = None,
    ):
        from fastapi.responses import Response as RawResponse

        try:
            body, content_type = core.debug_trace(
                format, rid=rid, trace=trace,
            )
        except ValueError as exc:
            raise HTTPException(status_code=422, detail=str(exc))
        if isinstance(body, str):
            return RawResponse(body, media_type=content_type)
        return body  # chrome/stitched: plain JSON

    @app.get("/debug/slo")
    async def debug_slo():
        try:
            return core.debug_slo()
        except ValueError as exc:
            raise HTTPException(status_code=422, detail=str(exc))

    @app.get("/debug/fleet")
    async def debug_fleet():
        try:
            return core.debug_fleet()
        except ValueError as exc:
            raise HTTPException(status_code=422, detail=str(exc))

    @app.get("/debug/rollout")
    async def debug_rollout():
        try:
            return core.debug_rollout()
        except ValueError as exc:
            raise HTTPException(status_code=422, detail=str(exc))

    @app.get("/debug/goodput")
    async def debug_goodput():
        try:
            return core.debug_goodput()
        except ValueError as exc:
            raise HTTPException(status_code=422, detail=str(exc))

    @app.get("/debug/tail")
    async def debug_tail(metric: str = "", n: Optional[int] = None):
        try:
            return core.debug_tail(metric=metric, n=n)
        except (ValueError, TypeError) as exc:
            raise HTTPException(status_code=422, detail=str(exc))

    # one middleware gives every route the X-Request-ID header, the
    # traceparent echo (predict endpoints already set their recorded
    # server context — setdefault keeps it), and the per-endpoint
    # request/error/latency series, through the SAME
    # ServingApp.observe_request the stdlib transport uses
    @app.middleware("http")
    async def telemetry_middleware(request, call_next):
        rid = telemetry.new_request_id()
        t0 = time.perf_counter()
        try:
            # same boundary validation as the stdlib transport: a
            # hostile X-Tenant-ID, X-Priority, or X-Model-Version
            # answers 422 before any route runs
            tenant = validate_tenant(request.headers.get("x-tenant-id"))
            priority = validate_priority(request.headers.get("x-priority"))
            model_version = validate_model_version(
                request.headers.get("x-model-version")
            )
        except ValueError as exc:
            from fastapi.responses import JSONResponse

            core.observe_request(
                "fastapi", request.url.path, 422,
                (time.perf_counter() - t0) * 1e3,
            )
            return JSONResponse(
                {"detail": str(exc)}, status_code=422,
                headers={"X-Request-ID": rid},
            )
        try:
            response = await call_next(request)
        except BaseException:
            # an unhandled endpoint error becomes a 500 OUTSIDE this
            # middleware — record it or error traffic is invisible in
            # /metrics (the stdlib transport records it via try/finally)
            core.observe_request(
                "fastapi", request.url.path, 500,
                (time.perf_counter() - t0) * 1e3,
            )
            raise
        # the predict routes set their OWN X-Request-ID (the id their
        # recorded timeline is keyed by — /debug/trace?rid= must
        # resolve it); the middleware fills it everywhere else
        if "X-Request-ID" not in response.headers:
            response.headers["X-Request-ID"] = rid
        response.headers["X-Tenant-ID"] = tenant
        response.headers["X-Priority"] = priority
        response.headers["X-Model-Version"] = model_version
        if "traceparent" not in response.headers:
            response.headers["traceparent"] = telemetry.format_traceparent(
                telemetry.server_trace_context(
                    request.headers.get("traceparent")
                )
            )
        core.observe_request(
            "fastapi", request.url.path, response.status_code,
            (time.perf_counter() - t0) * 1e3,
        )
        return response

    app.state.unionml_tpu = core
    return app
