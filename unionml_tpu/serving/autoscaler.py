"""SLO-driven fleet autoscaler: the loop that operates the fleet.

The :class:`~unionml_tpu.serving.router.FleetRouter` has every actuator
(``add_replica``/``remove_replica``, drain/join choreography,
``min_live``) and the stack emits every signal (per-replica queue depth
and breaker state, :meth:`~unionml_tpu.slo.SloWatchdog.burn_score`, the
usage ledger's decode capacity headroom) — but nothing closed the loop:
an operator scaled the fleet by hand, and a freshly joined replica
served cold. This module is the closing piece
(docs/robustness.md "Autoscaling & self-healing"):

- :class:`FleetAutoscaler` evaluates fleet health on a deterministic
  injectable clock (``evaluate(now=...)``, synthetic-clock testable
  exactly like ``SloWatchdog.evaluate``) and acts through the router's
  existing actuators. **Scale out** on sustained SLO burn — the fast
  window must burn hard AND the slow window must confirm it for
  ``sustain_evals`` consecutive evaluations, the same multiwindow
  discipline Google-SRE paging uses, so a blip never buys hardware —
  or on capacity-headroom exhaustion (recent-window deltas of
  :meth:`~unionml_tpu.serving.usage.UsageLedger.capacity_totals`), or
  to repair the fleet back to ``min_replicas`` after a replica dies.
  **Scale in** by draining the coldest-cache, lowest-load replica, and
  only when the *projected post-removal* headroom still clears the
  ``headroom_in`` hysteresis band — never below ``min_replicas`` (or
  the router's own ``min_live`` floor), never while any breaker is
  open, a replica is mid-recovery (ejected/half-open), or a drain is
  in flight: scale decisions must not fight failure recovery.
- new capacity is **fleet-warmed before it is routable**
  (Mooncake/SGLang cache-aware lineage): the join hook exports the
  warmest donor replica's hottest prefix blocks
  (:meth:`~unionml_tpu.serving.prefix_cache.RadixPrefixCache
  .export_hot` — host-RAM block entries under lease pinning) and
  imports them into the joiner *before* ``add_replica`` opens traffic,
  so a fresh replica's first requests hit warm prefixes instead of
  recomputing them.
- replicas come from a :class:`ReplicaProvisioner`:
  :class:`EngineReplicaProvisioner` builds in-process
  :class:`~unionml_tpu.serving.router.EngineReplica` s (tests,
  benches, single-host multi-engine), :class:`HttpReplicaProvisioner`
  wraps a spawn callable returning a base URL (subprocess / container
  / cloud API — the real path). A provision failure schedules an
  exponential-backoff retry and the autoscaler keeps evaluating — a
  broken provisioner degrades scaling, it never wedges the loop.

Every decision is explainable post-hoc: a flight-recorder event
(``scale_out`` / ``scale_in`` / ``scale_hold`` with its reason and the
signals that drove it) plus the ``unionml_autoscaler_*`` series
(decision counters by reason, live-replica and recent-headroom gauges,
provision failures, warmed blocks). Reasons are a CLOSED set
(:data:`DECISION_REASONS`) so label cardinality stays bounded.

Deterministic by construction: no wall clock (``clock`` is injectable
monotonic seconds), no randomness; ``start()``/``stop()`` run the
production ticker on a daemon thread exactly like the SLO watchdog's.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from unionml_tpu import telemetry
from unionml_tpu._logging import logger
from unionml_tpu.serving.router import (
    EngineReplica,
    FleetRouter,
    HttpReplica,
    ReplicaHandle,
)
from unionml_tpu.serving.scheduler import validate_phase

__all__ = [
    "AutoscalerPolicy",
    "DECISION_REASONS",
    "EngineReplicaProvisioner",
    "FleetAutoscaler",
    "HttpReplicaProvisioner",
    "ReplicaProvisioner",
]

# the CLOSED reason vocabulary (metric label values + flight-event
# reasons; free-form detail rides the flight event's other fields)
DECISION_REASONS = (
    # scale_out
    "below_min",          # self-healing: routable count under min_replicas
    "slo_burn",           # sustained fast+slow-window burn
    "headroom",           # recent decode headroom under headroom_out
    # scale_in
    "surplus",            # projected post-removal headroom clears the band
    "idle",               # no capacity-bearing traffic since last eval
    # scale_hold
    "steady",             # nothing to do
    "at_max",             # out wanted, max_replicas cap reached
    "cooldown_out",       # out wanted, per-direction cooldown running
    "cooldown_in",        # in wanted, per-direction cooldown running
    "breaker_open",       # in wanted, a replica's circuit breaker is open
    "recovery_in_flight",  # in wanted, a replica is ejected/half-open
    "drain_in_flight",    # a drain is running (fleet or replica)
    "min_live",           # in wanted, would breach the routable floor
    "no_pool_victim",     # in wanted, but every drainable candidate is a
    #                       shared colocated replica this POOL autoscaler
    #                       observes without owning
    "provision_failed",   # provisioner raised; backoff retry scheduled
    "provision_backoff",  # out wanted, still inside the failure backoff
)


class ReplicaProvisioner:
    """Where new replicas come from (and where removed ones go).

    The autoscaler's only dependency on infrastructure: ``provision``
    must return a routable :class:`~unionml_tpu.serving.router
    .ReplicaHandle` named ``name`` (raise on failure — the autoscaler
    retries with exponential backoff), ``release`` tears down a
    replica the autoscaler previously provisioned and has already
    drained + removed from the router."""

    def provision(self, name: str) -> ReplicaHandle:
        raise NotImplementedError

    def release(self, handle: ReplicaHandle) -> None:
        """Default: close the handle (subclasses stop the process /
        delete the VM / return the engine to a pool)."""
        handle.close()


class EngineReplicaProvisioner(ReplicaProvisioner):
    """In-process provisioner: ``factory() -> (engine, params)`` builds
    a fresh :class:`~unionml_tpu.serving.engine.DecodeEngine` (tests,
    benches, and single-host multi-engine deployments). ``release``
    closes the engine, so a scale-in actually frees its device
    memory."""

    def __init__(self, factory: Callable[[], tuple]):
        self._factory = factory

    def provision(self, name: str) -> ReplicaHandle:
        engine, params = self._factory()
        return EngineReplica(engine, params, name=name)

    def release(self, handle: ReplicaHandle) -> None:
        engine = getattr(handle, "engine", None)
        if engine is not None:
            engine.close()
        handle.close()


class HttpReplicaProvisioner(ReplicaProvisioner):
    """The real-path stub: ``spawn(name) -> base_url`` launches a
    serving process somewhere (subprocess, container, cloud API) and
    returns its URL; the handle is an :class:`~unionml_tpu.serving
    .router.HttpReplica` over it. ``teardown(handle)`` (optional)
    reverses the spawn on scale-in. Extra kwargs pass through to
    :class:`~unionml_tpu.serving.router.HttpReplica` (timeouts, peek
    TTL)."""

    def __init__(
        self,
        spawn: Callable[[str], str],
        *,
        teardown: Optional[Callable[[ReplicaHandle], None]] = None,
        **replica_kwargs,
    ):
        self._spawn = spawn
        self._teardown = teardown
        self._replica_kwargs = dict(replica_kwargs)

    def provision(self, name: str) -> ReplicaHandle:
        base_url = self._spawn(name)
        return HttpReplica(base_url, name=name, **self._replica_kwargs)

    def release(self, handle: ReplicaHandle) -> None:
        if self._teardown is not None:
            self._teardown(handle)
        handle.close()


class AutoscalerPolicy:
    """Tunables for :class:`FleetAutoscaler` (one object, bench/test
    sweeps name their configuration in one place — RouterPolicy's
    convention).

    **Scale-out triggers.** Sustained SLO burn: the fast window must
    burn at ``fast_burn_threshold`` AND the slow window at
    ``slow_burn_threshold`` for ``sustain_evals`` consecutive
    evaluations (defaults 2.0/1.0 × budget — scaling acts *earlier*
    than the 14.4/6 paging thresholds: hardware is cheaper than a
    page). Headroom: the recent-window decode headroom (deltas of the
    ledger's capacity counters between evaluations) under
    ``headroom_out``. Self-healing: routable replicas under
    ``min_replicas`` scales out immediately, cooldown exempt — repair
    must not wait out a cooldown that a scale action started.

    **Scale-in trigger + hysteresis.** Only when burn is fully clear
    (fast window ≤ ``burn_clear``) and the *projected post-removal*
    headroom — current utilization re-spread over one fewer replica,
    ``1 - (1 - headroom) * live / (live - 1)`` — still clears
    ``headroom_in``. The band between ``headroom_out`` and
    ``headroom_in`` is the hysteresis that keeps out/in from
    oscillating: with the defaults (0.1 / 0.5) a removal is only
    attempted when the survivors would still run under half capacity,
    so the removal itself cannot re-trigger a scale-out.

    **Cooldowns** are per-direction (``cooldown_out_s`` short — under-
    capacity hurts users; ``cooldown_in_s`` long — flapping hurts
    caches) and only start on a *successful* action.

    **Provision failures** back off exponentially
    (``provision_backoff_s · 2^(failures-1)`` capped at
    ``provision_backoff_max_s``) without blocking evaluation.

    **Reaping.** A replica that stays dead — ejected, or its health
    probe unreachable, for ``reap_unhealthy_evals`` consecutive
    evaluations — is removed from the router and released (flight
    event ``scale_reap``): a crashed process that will never rejoin
    must not pin the fleet at ``max_replicas`` and block its own
    replacement, nor hold scale-in hostage forever. The dead replica
    was not routable, so reaping changes membership, never capacity;
    the ``below_min`` repair path then provisions the replacement.

    ``warm_blocks`` bounds the donor export per join (0 disables fleet
    warming); ``drain_timeout_s`` bounds the scale-in drain.
    """

    def __init__(
        self,
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        fast_burn_threshold: float = 2.0,
        slow_burn_threshold: float = 1.0,
        sustain_evals: int = 2,
        burn_clear: float = 0.0,
        headroom_out: float = 0.1,
        headroom_in: float = 0.5,
        cooldown_out_s: float = 30.0,
        cooldown_in_s: float = 120.0,
        provision_backoff_s: float = 1.0,
        provision_backoff_max_s: float = 30.0,
        warm_blocks: int = 64,
        drain_timeout_s: float = 30.0,
        reap_unhealthy_evals: int = 4,
        name_prefix: str = "auto",
    ):
        if reap_unhealthy_evals < 1:
            raise ValueError(
                f"reap_unhealthy_evals must be >= 1, got "
                f"{reap_unhealthy_evals}"
            )
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas {max_replicas} < min_replicas {min_replicas}"
            )
        if sustain_evals < 1:
            raise ValueError(f"sustain_evals must be >= 1, got {sustain_evals}")
        if not 0.0 <= headroom_out < headroom_in <= 1.0:
            raise ValueError(
                f"need 0 <= headroom_out < headroom_in <= 1 (the "
                f"hysteresis band), got {headroom_out} / {headroom_in}"
            )
        if warm_blocks < 0:
            raise ValueError(f"warm_blocks must be >= 0, got {warm_blocks}")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.fast_burn_threshold = float(fast_burn_threshold)
        self.slow_burn_threshold = float(slow_burn_threshold)
        self.sustain_evals = int(sustain_evals)
        self.burn_clear = float(burn_clear)
        self.headroom_out = float(headroom_out)
        self.headroom_in = float(headroom_in)
        self.cooldown_out_s = float(cooldown_out_s)
        self.cooldown_in_s = float(cooldown_in_s)
        self.provision_backoff_s = float(provision_backoff_s)
        self.provision_backoff_max_s = float(provision_backoff_max_s)
        self.warm_blocks = int(warm_blocks)
        self.drain_timeout_s = float(drain_timeout_s)
        self.reap_unhealthy_evals = int(reap_unhealthy_evals)
        self.name_prefix = str(name_prefix)


class FleetAutoscaler:
    """The closed loop over a :class:`~unionml_tpu.serving.router
    .FleetRouter` (module docstring has the full story).

    Args:
        router: the fleet to operate.
        provisioner: where new replicas come from.
        policy: :class:`AutoscalerPolicy` (defaults are conservative).
        slo: an optional fleet-level :class:`~unionml_tpu.slo
            .SloWatchdog` — evaluated each tick on the autoscaler's
            clock for the sustained fast+slow burn trigger. Without
            one, the max per-replica ``burn`` from the replicas' own
            health dicts stands in for BOTH windows (replica
            watchdogs only refresh the fast read).
        usage: an optional :class:`~unionml_tpu.serving.usage
            .UsageLedger` shared by the replica engines — its capacity
            counters, differenced between evaluations, are the
            recent-window headroom signal. Without one, scale-in can
            only infer "idle" from empty replica queues (queued work
            anywhere always holds scale-in), which cannot see
            decode-in-flight work — wire a ledger for load-aware
            consolidation.
        registry / flight: explicit telemetry sinks (process-global by
            default).
        clock: injectable monotonic seconds — deterministic tests pass
            a synthetic clock and drive :meth:`evaluate(now=...)
            <evaluate>` directly.
    """

    def __init__(
        self,
        router: FleetRouter,
        provisioner: ReplicaProvisioner,
        *,
        policy: Optional[AutoscalerPolicy] = None,
        slo=None,
        usage=None,
        registry: Optional[telemetry.MetricsRegistry] = None,
        flight: Optional[telemetry.FlightRecorder] = None,
        clock: Callable[[], float] = time.monotonic,
        phase: Optional[str] = None,
    ):
        self.router = router
        self.provisioner = provisioner
        self.policy = policy if policy is not None else AutoscalerPolicy()
        # per-pool scaling (docs/serving.md "Disaggregated serving"):
        # when set, this autoscaler observes the replicas of its phase
        # PLUS shared colocated members (the phase-aware router routes
        # either leg to those, so they are real pool capacity and
        # their corpses must be reaped by somebody), but only ACTS —
        # scale-in drains — on exact-phase members it owns. A
        # phase-split fleet runs one autoscaler per pool, each with
        # its own signal wiring (a TTFT-objective watchdog scales
        # prefill; the decode pool's ledger headroom scales decode)
        # and its own min/max band. Provisioned replicas are stamped
        # with the phase so the router's phase-aware pick and the
        # next evaluation both see them in the right pool.
        # None (default) operates the whole fleet — the single-pool
        # behavior, unchanged.
        self.phase = None if phase is None else validate_phase(phase)
        # pool-scoped names: two pool autoscalers (possibly sharing
        # one policy object — custom name_prefix included) each start
        # their counters at 0, so the phase must be IN the name or
        # the second pool's first scale-out dies on the router's
        # name-collision join check exactly when it needed capacity
        self._name_prefix = (
            f"{self.policy.name_prefix}-{self.phase}"
            if self.phase is not None else self.policy.name_prefix
        )
        self._slo = slo
        self._usage = usage
        self._clock = clock
        self._registry = (
            registry if registry is not None else telemetry.get_registry()
        )
        self._flight = (
            flight if flight is not None else telemetry.get_flight_recorder()
        )
        self._eval_lock = threading.Lock()
        self._burn_streak = 0
        self._last_out_at = float("-inf")
        # scale-in starts its cooldown at the FIRST evaluation: a
        # just-started autoscaler must not shrink a fleet it has only
        # observed for one tick (scale-out stays immediate — under-
        # capacity hurts users, a grace period doesn't)
        self._last_in_at: Optional[float] = None
        self._provision_failures = 0
        self._provision_retry_at = float("-inf")
        self._next_id = 0
        self._provisioned: Dict[str, ReplicaHandle] = {}
        self._last_cap = 0.0
        self._last_used = 0.0
        self._unhealthy_streak: Dict[str, int] = {}
        self._last_decision: Optional[dict] = None
        self._ticker: Optional[threading.Thread] = None
        self._ticker_stop = threading.Event()
        # the fleet dashboard (GET /debug/fleet on the router app)
        # reads the operating autoscaler's view through this link;
        # phase-split fleets additionally register per pool, so the
        # dashboard can show every pool's autoscaler side by side
        router.autoscaler = self
        if isinstance(getattr(router, "autoscalers", None), dict):
            router.autoscalers[self.phase or "fleet"] = self
        R = self._registry
        self._m_decisions = R.counter(
            "unionml_autoscaler_decisions_total",
            "Autoscaler decisions by kind and (closed-set) reason — "
            "every evaluation lands in exactly one child, so the "
            "decision stream is reconstructible from counters alone.",
            ("decision", "reason"),
        )
        self._m_provision_failures = R.counter(
            "unionml_autoscaler_provision_failures_total",
            "Provisioner failures during scale-out (each schedules an "
            "exponential-backoff retry; the loop never wedges).",
        )
        self._m_warmed = R.counter(
            "unionml_autoscaler_warmed_blocks_total",
            "Prefix-cache blocks imported into joining replicas from "
            "warm-donor exports (fleet-warmed joins).",
        )
        self._m_reaped = R.counter(
            "unionml_autoscaler_reaped_total",
            "Dead replicas (ejected/unreachable for reap_unhealthy_"
            "evals consecutive evaluations) removed from the router "
            "so their replacement can provision.",
        )
        self._g_replicas = R.gauge(
            "unionml_autoscaler_replicas",
            "Routable replicas (live or half-open) at the last "
            "autoscaler evaluation.",
        )
        self._g_headroom = R.gauge(
            "unionml_autoscaler_headroom",
            "Recent-window decode capacity headroom at the last "
            "evaluation (1.0 when no ledger is wired or no "
            "capacity-bearing traffic flowed).",
        )

    # -- signals -----------------------------------------------------------

    def _burn(self, signals: Dict[str, dict], now: float) -> Dict[str, float]:
        if self._slo is not None:
            self._slo.evaluate(now=now)
            return self._slo.burn_scores()
        # no fleet watchdog: the replicas' own health-dict burn (their
        # per-replica watchdogs' fast window) stands in for both
        replica_burn = max(
            (
                float(s["health"].get("burn", 0.0) or 0.0)
                for s in signals.values()
            ),
            default=0.0,
        )
        return {"fast": replica_burn, "slow": replica_burn}

    def _recent_headroom(self) -> "tuple[float, bool]":
        """``(headroom, traffic_flowed)`` over the window since the
        previous evaluation — counter deltas, so an idle morning never
        dilutes an overloaded afternoon."""
        if self._usage is None:
            return 1.0, False
        cap, used = self._usage.capacity_totals()
        d_cap = cap - self._last_cap
        d_used = used - self._last_used
        self._last_cap, self._last_used = cap, used
        if d_cap <= 0.0:
            return 1.0, False
        return max(0.0, 1.0 - d_used / d_cap), True

    # -- the decision ------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One decision: gather signals, decide scale out / in / hold,
        act, and record it (flight event + counters). Deterministic
        for a given ``now`` and fleet state; the production ticker
        calls this with no argument."""
        with self._eval_lock:
            if now is None:
                now = self._clock()
            return self._evaluate_locked(now)

    def _pool_signals(self, signals: Dict[str, dict]) -> Dict[str, dict]:
        """Restrict a fleet signal sweep to this autoscaler's pool
        (no-op for a fleet-wide autoscaler). COLOCATED replicas are
        included — the phase-aware router routes either leg to them,
        so they are real pool capacity (and their corpses must still
        be reaped by SOMEBODY in a fleet running only pool
        autoscalers); :meth:`_owned` narrows back to exact-phase
        members wherever the autoscaler ACTS rather than observes.
        Phase rides the signal dicts, so filtering costs no extra
        probes."""
        if self.phase is None:
            return signals
        return {
            n: s for n, s in signals.items()
            if s.get("phase", "colocated") in (self.phase, "colocated")
        }

    def _owned(self, signals: Dict[str, dict]) -> Dict[str, dict]:
        """The members this autoscaler may DRAIN (scale-in victims):
        exact-phase only — a shared colocated replica serves both
        pools, and one pool's consolidation must not remove capacity
        the other depends on."""
        if self.phase is None:
            return signals
        return {
            n: s for n, s in signals.items()
            if s.get("phase", "colocated") == self.phase
        }

    def _evaluate_locked(self, now: float) -> dict:
        p = self.policy
        if self._last_in_at is None:
            self._last_in_at = now
        signals = self._pool_signals(self.router.replica_signals())
        signals = self._reap_dead(signals)
        routable = {
            n: s for n, s in signals.items()
            if s["state"] in ("live", "half_open")
            and s["health"].get("status") not in ("unreachable", "draining")
        }
        live = len(routable)
        draining = [n for n, s in signals.items() if s["state"] == "draining"]
        # anything mid-failure-recovery: ejected/half-open router
        # state, or a dead-but-unreaped (unreachable) replica — while
        # any exists, scale-in must hold (never fight recovery)
        recovering = [
            n for n, s in signals.items()
            if s["state"] in ("ejected", "half_open")
            or s["health"].get("status") == "unreachable"
        ]
        breakers = [
            n for n, s in signals.items()
            if s["health"].get("breaker_open")
        ]
        burn = self._burn(signals, now)
        headroom, traffic = self._recent_headroom()
        self._g_replicas.set(float(live))
        self._g_headroom.set(headroom)

        burn_hot = (
            burn["fast"] >= p.fast_burn_threshold
            and burn["slow"] >= p.slow_burn_threshold
        )
        self._burn_streak = self._burn_streak + 1 if burn_hot else 0
        detail = {
            "live": live,
            "burn_fast": round(burn["fast"], 4),
            "burn_slow": round(burn["slow"], 4),
            "burn_streak": self._burn_streak,
            "headroom": round(headroom, 4),
            "traffic": traffic,
        }

        fleet_draining = (
            self.router.health().get("status") == "draining" or draining
        )

        # -- scale OUT ---------------------------------------------------
        out_reason = None
        if live < p.min_replicas:
            out_reason = "below_min"       # repair: cooldown exempt
        elif self._burn_streak >= p.sustain_evals:
            out_reason = "slo_burn"
        elif traffic and headroom < p.headroom_out:
            out_reason = "headroom"
        if out_reason is not None:
            if fleet_draining:
                return self._hold(now, "drain_in_flight", detail)
            if len(signals) >= p.max_replicas:
                return self._hold(now, "at_max", detail)
            if (
                out_reason != "below_min"
                and now - self._last_out_at < p.cooldown_out_s
            ):
                return self._hold(now, "cooldown_out", detail)
            if now < self._provision_retry_at:
                return self._hold(now, "provision_backoff", detail)
            return self._scale_out(now, out_reason, routable, detail)

        # -- scale IN ----------------------------------------------------
        projected = 1.0
        if live > 1:
            projected = 1.0 - (1.0 - headroom) * live / (live - 1)
        # the "idle" path has NO capacity measurement behind it (no
        # ledger, or no capacity-bearing dispatches since last eval),
        # so it additionally requires every routable queue to be empty
        # — without this, a fleet run with usage=None and no burn
        # source would read every evaluation as idle and shrink itself
        # under full load. The "surplus" path rides the measured
        # headroom signal and keeps its hysteresis-band gate.
        queued = sum(
            float(s["health"].get("queue_depth", 0) or 0)
            for s in routable.values()
        )
        want_in = (
            live > p.min_replicas
            and burn["fast"] <= p.burn_clear
            and self._burn_streak == 0
            and (
                (traffic and projected > p.headroom_in)
                or (not traffic and queued == 0.0)
            )
        )
        if want_in:
            detail["projected_headroom"] = round(projected, 4)
            # scale-in must never fight failure recovery
            if fleet_draining:
                return self._hold(now, "drain_in_flight", detail)
            if breakers:
                return self._hold(
                    now, "breaker_open", {**detail, "replicas": breakers},
                )
            if recovering:
                return self._hold(
                    now, "recovery_in_flight",
                    {**detail, "replicas": recovering},
                )
            if live - 1 < self.router.policy.min_live:
                return self._hold(now, "min_live", detail)
            if now - self._last_in_at < p.cooldown_in_s:
                return self._hold(now, "cooldown_in", detail)
            victims = self._owned(routable)
            if not victims:
                # every drainable candidate is shared colocated
                # capacity this pool autoscaler observes but does not
                # own — consolidating it would steal from the peer pool
                return self._hold(now, "no_pool_victim", detail)
            reason = "surplus" if traffic else "idle"
            return self._scale_in(now, reason, victims, detail)

        return self._hold(now, "steady", detail)

    # -- actions -----------------------------------------------------------

    def _reap_dead(self, signals: Dict[str, dict]) -> Dict[str, dict]:
        """Remove replicas that stayed dead (ejected / unreachable) for
        ``reap_unhealthy_evals`` consecutive evaluations; returns the
        signal set without them. A corpse is not routable, so this
        changes membership, never capacity — and it frees the
        ``max_replicas`` slot its replacement needs."""
        p = self.policy
        for name in list(self._unhealthy_streak):
            if name not in signals:
                self._unhealthy_streak.pop(name)
        reaped: List[str] = []
        for name, s in signals.items():
            dead = (
                s["state"] == "ejected"
                or s["health"].get("status") == "unreachable"
            )
            streak = self._unhealthy_streak.get(name, 0) + 1 if dead else 0
            self._unhealthy_streak[name] = streak
            if (
                dead and streak >= p.reap_unhealthy_evals
                and s["state"] != "draining"
            ):
                reaped.append(name)
        removed: List[str] = []
        for name in reaped:
            logger.info(f"autoscaler: reaping dead replica {name}")
            try:
                self.router.remove_replica(name, drain_timeout=0.0)
            except BaseException as exc:
                # removal failed: record NOTHING — the corpse is still
                # a member, keeps its streak, and is retried next
                # evaluation (a premature counter/event would claim a
                # reap that never happened and re-grant the grace
                # period)
                logger.info(
                    f"autoscaler: reap of {name} failed ({exc!r})"
                )
                continue
            removed.append(name)
            evals = self._unhealthy_streak.pop(name, 0)
            self._flight.record("scale_reap", replica=name, evals=evals)
            self.router.trace_event("scale_reap", replica=name, evals=evals)
            self._m_reaped.inc()
            handle = self._provisioned.pop(name, None)
            if handle is not None:
                try:
                    self.provisioner.release(handle)
                except BaseException:
                    pass
        if removed:
            signals = {
                n: s for n, s in signals.items() if n not in removed
            }
        return signals

    def _record(self, decision: str, reason: str, detail: dict) -> dict:
        self._m_decisions.labels(decision, reason).inc()
        out = {"decision": decision, "reason": reason, **detail}
        self._last_decision = out
        if decision != "scale_hold" or reason != "steady":
            # every acted-or-blocked decision is also a span EVENT on
            # the router's fleet timeline (OTLP export), so a latency
            # spike and the scale decision that caused — or failed to
            # prevent — it sit on one trace axis. Steady holds stay
            # off the timeline for the same reason they stay out of
            # the flight ring.
            self.router.trace_event(decision, reason=reason, **{
                k: v for k, v in detail.items() if k != "traffic"
            })
        return out

    def _hold(self, now: float, reason: str, detail: dict) -> dict:
        # steady holds stay out of the flight ring (a 5 s ticker would
        # flush real request events in hours); every OTHER hold — a
        # trigger wanted an action and a guard stopped it — is recorded
        if reason != "steady":
            self._flight.record("scale_hold", reason=reason, **{
                k: v for k, v in detail.items() if k != "traffic"
            })
        return self._record("scale_hold", reason, detail)

    def _scale_out(
        self, now: float, reason: str,
        routable: Dict[str, dict], detail: dict,
    ) -> dict:
        p = self.policy
        name = f"{self._name_prefix}-{self._next_id}"
        try:
            handle = self.provisioner.provision(name)
            if self.phase is not None:
                # the joiner belongs to this autoscaler's pool: the
                # phase-aware pick and the next evaluation's filter
                # both key on the handle's tag
                handle.phase = self.phase
        except BaseException as exc:
            self._provision_failures += 1
            backoff = min(
                p.provision_backoff_s * (2 ** (self._provision_failures - 1)),
                p.provision_backoff_max_s,
            )
            self._provision_retry_at = now + backoff
            self._m_provision_failures.inc()
            self._flight.record(
                "scale_hold", reason="provision_failed", replica=name,
                error=f"{type(exc).__name__}: {exc}",
                retry_in_s=round(backoff, 3), **detail,
            )
            logger.info(
                f"autoscaler: provision {name} failed ({exc!r}); "
                f"retrying in {backoff:.1f}s"
            )
            return self._record("scale_hold", "provision_failed", detail)
        self._provision_failures = 0
        self._provision_retry_at = float("-inf")
        self._next_id += 1

        # fleet-warm the joiner BEFORE it takes traffic: hottest blocks
        # from the warmest donor (most resident cache blocks)
        donor_name, imported = None, 0
        if p.warm_blocks > 0 and routable:
            donor_name = max(
                routable, key=lambda n: (routable[n]["cache_blocks"], n),
            )
            if routable[donor_name]["cache_blocks"] <= 0:
                donor_name = None
        if donor_name is not None:
            try:
                donor = self.router.replica_handle(donor_name)
                entries = donor.export_hot_blocks(max_blocks=p.warm_blocks)
                imported = int(handle.import_cache_blocks(entries))
            except BaseException as exc:  # warming is best-effort
                logger.info(
                    f"autoscaler: warm-join from {donor_name} failed "
                    f"({exc!r}); {name} joins cold"
                )
                imported = 0
        if imported:
            self._m_warmed.inc(imported)

        try:
            self.router.add_replica(handle)     # now routable
        except BaseException as exc:
            # a join failure (e.g. a name collision with an operator-
            # registered replica) must release the handle — a leaked
            # engine pins device memory for the process lifetime —
            # and surface as a decision, not an exception out of
            # evaluate(); _next_id already advanced, so the retry
            # picks a fresh name
            try:
                self.provisioner.release(handle)
            except BaseException:
                pass
            self._m_provision_failures.inc()
            self._flight.record(
                "scale_hold", reason="provision_failed", replica=name,
                error=f"{type(exc).__name__}: {exc}", **{
                    k: v for k, v in detail.items() if k != "traffic"
                },
            )
            logger.info(
                f"autoscaler: join of {name} failed ({exc!r})"
            )
            return self._record("scale_hold", "provision_failed", detail)
        self._provisioned[name] = handle
        self._last_out_at = now
        self._burn_streak = 0
        self._flight.record(
            "scale_out", replica=name, reason=reason,
            donor=donor_name, warmed_blocks=imported, **{
                k: v for k, v in detail.items() if k != "traffic"
            },
        )
        logger.info(
            f"autoscaler: scale out -> {name} ({reason}; donor="
            f"{donor_name}, warmed {imported} blocks)"
        )
        return self._record("scale_out", reason, {
            **detail, "replica": name, "donor": donor_name,
            "warmed_blocks": imported,
        })

    def _scale_in(
        self, now: float, reason: str,
        routable: Dict[str, dict], detail: dict,
    ) -> dict:
        # victim: coldest cache first, then lowest load, then name (a
        # deterministic tie-break the tests rely on)
        victim = min(
            routable,
            key=lambda n: (
                routable[n]["cache_blocks"],
                float(routable[n]["health"].get("queue_depth", 0)),
                n,
            ),
        )
        self._flight.record(
            "scale_in", replica=victim, reason=reason,
            cache_blocks=routable[victim]["cache_blocks"],
            queue_depth=routable[victim]["health"].get("queue_depth", 0),
            **{k: v for k, v in detail.items() if k != "traffic"},
        )
        drained = self.router.remove_replica(
            victim, drain_timeout=self.policy.drain_timeout_s,
        )
        handle = self._provisioned.pop(victim, None)
        if handle is not None:
            try:
                self.provisioner.release(handle)
            except BaseException as exc:
                logger.info(
                    f"autoscaler: release of {victim} failed ({exc!r})"
                )
        self._last_in_at = now
        logger.info(
            f"autoscaler: scale in -> removed {victim} ({reason}, "
            f"drained={drained})"
        )
        return self._record("scale_in", reason, {
            **detail, "replica": victim, "drained": drained,
        })

    # -- views / lifecycle -------------------------------------------------

    def stats(self) -> dict:
        with self._eval_lock:
            return {
                "last_decision": dict(self._last_decision or {}),
                "burn_streak": self._burn_streak,
                "provisioned": sorted(self._provisioned),
                "provision_failures": self._provision_failures,
            }

    def dashboard(self, signals: Optional[Dict[str, dict]] = None) -> dict:
        """The operator view ``GET /debug/fleet`` serves (through
        :meth:`~unionml_tpu.serving.router.FleetRouter.fleet_report`,
        which passes its already-gathered ``signals`` so one dashboard
        call costs one fleet sweep, not three): the burn windows and
        usage headroom the next decision will read, plus the last
        decision and its reason. READ-ONLY and NON-BLOCKING for the
        decision loop — the headroom is computed against the stored
        counters without advancing them, the burn read is the
        watchdog's last evaluation, and any replica health sweep
        happens OUTSIDE ``_eval_lock`` (a wedged remote replica under
        the lock would stall the very evaluation the autoscaler
        exists to make)."""
        if self._slo is not None:
            burn = self._slo.burn_scores()
        else:
            # the replica-health fallback may touch the network —
            # never under the evaluation lock
            if signals is None:
                signals = self.router.replica_signals()
            pool = self._pool_signals(signals)
            replica_burn = max(
                (
                    float(s["health"].get("burn", 0.0) or 0.0)
                    for s in pool.values()
                ),
                default=0.0,
            )
            burn = {"fast": replica_burn, "slow": replica_burn}
        with self._eval_lock:
            headroom, traffic = 1.0, False
            if self._usage is not None:
                cap, used = self._usage.capacity_totals()
                d_cap = cap - self._last_cap
                d_used = used - self._last_used
                if d_cap > 0.0:
                    headroom = max(0.0, 1.0 - d_used / d_cap)
                    traffic = True
            return {
                # which pool this autoscaler operates (None = the
                # whole fleet — the single-pool view, unchanged)
                "phase": self.phase,
                "burn": burn,
                "burn_streak": self._burn_streak,
                "headroom": round(headroom, 4),
                "traffic_since_last_eval": traffic,
                "last_decision": dict(self._last_decision or {}),
                "provisioned": sorted(self._provisioned),
                "provision_failures": self._provision_failures,
                "policy": {
                    "min_replicas": self.policy.min_replicas,
                    "max_replicas": self.policy.max_replicas,
                    "headroom_out": self.policy.headroom_out,
                    "headroom_in": self.policy.headroom_in,
                },
            }

    def start(self, interval_s: float = 5.0) -> None:
        """Evaluate every ``interval_s`` on a daemon thread (the
        production loop; deterministic tests drive :meth:`evaluate`
        directly). Idempotent."""
        if self._ticker is not None and self._ticker.is_alive():
            return
        self._ticker_stop.clear()

        def tick():
            while not self._ticker_stop.wait(interval_s):
                try:
                    self.evaluate()
                except Exception:
                    logger.info("autoscaler: evaluation failed", exc_info=True)

        self._ticker = threading.Thread(
            target=tick, daemon=True, name="unionml-tpu-autoscaler"
        )
        self._ticker.start()

    def stop(self) -> None:
        self._ticker_stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=2.0)
            self._ticker = None

    def close(self) -> None:
        """Stop the ticker and release every replica this autoscaler
        provisioned (for teardown paths; the router keeps serving with
        whatever remains registered)."""
        self.stop()
        for name, handle in list(self._provisioned.items()):
            try:
                self.provisioner.release(handle)
            except BaseException:
                pass
            self._provisioned.pop(name, None)
