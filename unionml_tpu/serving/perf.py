"""Serving goodput plane: batch-occupancy accounting + perf watchdog.

The serving-side twin of :mod:`unionml_tpu.goodput` (PR 7's training
goodput layer). The training tracker classifies trainer wall time into
compute vs. badput causes; this module classifies the decode engine's
*device passes* — every dispatcher pass lands in a bounded ring as one
of :data:`PASS_KINDS`:

- ``full_batch``  — every resident slot carried a live request; the
  chunk's slot-steps were all useful work.
- ``padded_slots`` — the chunk ran with empty slots; the padded
  slot-steps are the serving analogue of training's badput.
- ``prefill_mix`` — the chunk ran while a chunked admission was
  interleaving prefill into the decode cadence (useful, but decode
  throughput is degraded by the mixed program).
- ``idle`` — the dispatcher found no work at all (queue empty, no
  occupants); wall time with the device parked.

:class:`ServingPerfPlane` owns the ring, publishes the
``unionml_serving_goodput_ratio`` / ``unionml_serving_occupancy_ratio``
/ ``unionml_serving_kv_pressure_ratio`` gauges per engine, and carries
a :class:`ServingRegressionWatchdog` — rolling-baseline detectors
(reusing PR 7's :class:`~unionml_tpu.goodput
.StepTimeRegressionDetector` hysteresis) over TTFT, inter-token
latency, and the goodput ratio itself. Regression transitions emit
``perf_regression`` flight events whose ``reason`` comes from the
closed :data:`PERF_REGRESSION_REASONS` set (lint-enforced against
docs/observability.md, like the rollout decision reasons), and
:meth:`ServingRegressionWatchdog.advisory` is the signal the
autoscaler and the rollout SLO guard can poll.

Everything here is pure host math — no jax, no device work, no wall
clocks (``clock`` is injectable monotonic seconds) — so the
classification and hysteresis are unit-testable on synthetic traces,
and the hot-path cost per dispatcher pass is one deque append plus a
few float ops (the ``serve_perf`` bench holds the on/off p99 delta
under 1%).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from unionml_tpu import telemetry
from unionml_tpu.goodput import StepTimeRegressionDetector

__all__ = [
    "PASS_KINDS",
    "PERF_REGRESSION_REASONS",
    "ServingPerfPlane",
    "ServingRegressionWatchdog",
]

#: The device-pass taxonomy (docs/observability.md "Serving goodput &
#: tail attribution"). Every dispatcher pass is exactly one of these.
PASS_KINDS = (
    "full_batch",     # all slots occupied: pure useful decode
    "padded_slots",   # some slots empty: padded slot-steps wasted
    "prefill_mix",    # chunked admission interleaved into the cadence
    "idle",           # no work at all: device parked
)

#: Closed reasons vocabulary for ``perf_regression`` flight events —
#: lint-enforced both ways against the docs table, like
#: ROLLBACK/DECISION reasons (scripts/lint_basics.py).
PERF_REGRESSION_REASONS = (
    "ttft_regression",    # submit-to-first-token crossed the baseline band
    "itl_regression",     # inter-token latency crossed the baseline band
    "goodput_collapse",   # goodput ratio fell against its baseline
)

#: Feed the goodput watchdog every Nth dispatcher pass — the detector
#: wants a sampled trend, not one update per 2 ms chunk.
_GOODPUT_FEED_EVERY = 32

#: Goodput ratios are inverted (lower is worse) before they feed the
#: shared higher-is-worse detector; the floor keeps a cold-start 0.0
#: ratio from producing an unbounded inverse.
_GOODPUT_FLOOR = 0.05


class ServingRegressionWatchdog:
    """Rolling-baseline regression detection over serving perf signals.

    One :class:`StepTimeRegressionDetector` per
    :data:`PERF_REGRESSION_REASONS` entry. TTFT and ITL feed their
    detectors directly (ms, higher is worse); the goodput ratio feeds
    as ``1 / max(ratio, 0.05)`` so a collapse (ratio down) reads as a
    regression (value up) to the same hysteresis machinery. State
    *transitions* emit ``perf_regression`` flight events; the steady
    state is readable via :meth:`advisory` (what the autoscaler and
    rollout SLO guard poll).

    ``flight=None`` disables event emission (pure-math tests); the
    engine passes its recorder plus its ``engine``/``phase`` identity
    so fleet dumps attribute the event.
    """

    def __init__(
        self,
        *,
        flight: Optional[telemetry.FlightRecorder] = None,
        engine: str = "engine",
        phase: str = "colocated",
        window: int = 50,
        threshold: float = 1.5,
        clear_threshold: float = 1.2,
        consecutive: int = 3,
        min_samples: int = 10,
    ):
        self._flight = flight
        self._engine = engine
        self._phase = phase
        self._lock = threading.Lock()
        self._detectors: Dict[str, StepTimeRegressionDetector] = {
            reason: StepTimeRegressionDetector(
                window=window, threshold=threshold,
                clear_threshold=clear_threshold,
                consecutive=consecutive, min_steps=min_samples,
            )
            for reason in PERF_REGRESSION_REASONS
        }
        self._last_ratio = {r: 1.0 for r in PERF_REGRESSION_REASONS}

    def _feed(self, reason: str, value: float, raw: float) -> dict:
        with self._lock:
            verdict = self._detectors[reason].update(value)
            self._last_ratio[reason] = verdict["ratio"]
        if (verdict["entered"] or verdict["cleared"]) and (
            self._flight is not None
        ):
            tag = {} if self._phase == "colocated" else {"phase": self._phase}
            self._flight.record(
                "perf_regression",
                engine=self._engine,
                **tag,
                reason=reason,
                state="entered" if verdict["entered"] else "cleared",
                ratio=round(verdict["ratio"], 3),
                value=round(raw, 4),
            )
        return verdict

    def observe_ttft(self, ttft_ms: float) -> dict:
        """Feed one completed request's TTFT (ms)."""
        return self._feed("ttft_regression", float(ttft_ms), float(ttft_ms))

    def observe_itl(self, itl_ms: float) -> dict:
        """Feed one completed request's mean inter-token latency (ms)."""
        return self._feed("itl_regression", float(itl_ms), float(itl_ms))

    def observe_goodput(self, ratio: float) -> dict:
        """Feed one goodput-ratio sample (0..1, higher is better)."""
        ratio = float(ratio)
        return self._feed(
            "goodput_collapse", 1.0 / max(ratio, _GOODPUT_FLOOR), ratio
        )

    def advisory(self) -> dict:
        """The poll surface: ``{"regressed", "reasons", "detail"}`` —
        ``reasons`` lists the currently-regressed signals, ``detail``
        has each detector's live ratio/anomaly counters."""
        with self._lock:
            detail = {
                reason: {
                    "regressed": det.regressed,
                    "ratio": round(self._last_ratio[reason], 4),
                    "anomalies": det.anomalies,
                    "baseline": det.baseline(),
                }
                for reason, det in self._detectors.items()
            }
        active = [r for r in PERF_REGRESSION_REASONS if detail[r]["regressed"]]
        return {
            "regressed": bool(active),
            "reasons": active,
            "detail": detail,
        }


class ServingPerfPlane:
    """Bounded-ring device-pass accountant for one decode engine.

    The engine's dispatcher calls :meth:`note_pass` after every chunk
    dispatch and :meth:`note_idle` on every no-work pass; the
    harvester calls :meth:`note_tokens` per harvested chunk. The ring
    (newest ``ring`` passes) is the goodput window: ratios are over
    *recent* passes, so a burst of idle at startup ages out instead of
    depressing the gauge forever.

    - ``goodput_ratio``  = occupied slot-steps / all slot-steps in the
      ring (idle passes count the full batch as lost).
    - ``occupancy_ratio`` = occupied slot-steps / dispatched
      slot-steps (idle passes excluded — the padding-only view).
    - ``kv_pressure_ratio`` = blocks in use / pool capacity at the
      last dispatch pass.
    """

    def __init__(
        self,
        *,
        registry: Optional[telemetry.MetricsRegistry] = None,
        flight: Optional[telemetry.FlightRecorder] = None,
        engine: str = "engine",
        phase: str = "colocated",
        slots: int = 1,
        chunk_steps: int = 1,
        ring: int = 2048,
        clock: Callable[[], float] = time.perf_counter,
        watchdog: Optional[ServingRegressionWatchdog] = None,
    ):
        self._registry = (
            registry if registry is not None else telemetry.get_registry()
        )
        self._engine = engine
        self._phase = phase
        self._slots = max(1, int(slots))
        self._chunk_steps = max(1, int(chunk_steps))
        self._clock = clock
        self._lock = threading.Lock()
        # ring entries: (kind, occupied_slot_steps, total_slot_steps),
        # with the slot-step sums carried incrementally (evictions
        # subtract, appends add) so the per-pass ratio math is O(1) —
        # walking a 2048-entry ring per 2 ms dispatcher pass is what
        # the serve_perf bench exists to catch
        self._ring: deque = deque(maxlen=max(16, int(ring)))
        self._occ_steps = 0
        self._disp_steps = 0
        self._idle_steps = 0
        self._passes = 0
        self._tokens = 0
        self._t0 = clock()
        self._kv_pressure = 0.0
        self.watchdog = (
            watchdog
            if watchdog is not None
            else ServingRegressionWatchdog(
                flight=flight, engine=engine, phase=phase
            )
        )
        R, lbl = self._registry, {"engine": engine}

        def gauge(name, help):
            return R.gauge(name, help, ("engine",)).labels(**lbl)

        self._g_goodput = gauge(
            "unionml_serving_goodput_ratio",
            "Occupied slot-steps over all slot-steps in the recent "
            "dispatcher-pass ring (idle passes count the whole batch "
            "as lost; 1.0 = every pass was a full batch).",
        )
        self._g_occupancy = gauge(
            "unionml_serving_occupancy_ratio",
            "Occupied slot-steps over dispatched slot-steps in the "
            "recent ring (idle passes excluded: the padded-slot view).",
        )
        self._g_kv_pressure = gauge(
            "unionml_serving_kv_pressure_ratio",
            "KV pool blocks in use over pool capacity at the last "
            "dispatch pass (0 on non-paged engines).",
        )
        # lazy gauges, sampled at scrape/read time: the dispatcher
        # calls note_pass/note_idle every ~2 ms, and three eager
        # Gauge.set calls per pass are measurable against the
        # serve_perf bench's 1% p99 bar — the scrape path pays instead
        self._g_goodput.set_function(lambda: self._sample_ratios()[0])
        self._g_occupancy.set_function(lambda: self._sample_ratios()[1])
        self._g_kv_pressure.set_function(lambda: self._sample_ratios()[2])

    # -- dispatcher hooks --------------------------------------------------

    def note_pass(
        self,
        occupied: int,
        *,
        prefill_mix: bool = False,
        kv_in_use: int = 0,
        kv_capacity: int = 0,
    ) -> None:
        """One dispatched decode chunk: ``occupied`` slots carried live
        requests (of the engine's ``slots``); ``prefill_mix`` flags a
        chunk that ran while chunked admission was interleaving."""
        occupied = min(self._slots, max(0, int(occupied)))
        if prefill_mix:
            kind = "prefill_mix"
        elif occupied >= self._slots:
            kind = "full_batch"
        else:
            kind = "padded_slots"
        total = self._slots * self._chunk_steps
        occ = occupied * self._chunk_steps
        goodput = None
        with self._lock:
            self._append_locked(kind, occ, total)
            self._passes += 1
            if kv_capacity > 0:
                self._kv_pressure = min(
                    1.0, max(0.0, kv_in_use / kv_capacity)
                )
            if self._passes % _GOODPUT_FEED_EVERY == 0:
                goodput = self._ratios_locked()[0]
        if goodput is not None:
            self.watchdog.observe_goodput(goodput)

    def note_idle(self) -> None:
        """One dispatcher pass that found no work: the whole batch's
        slot-steps are classified idle."""
        total = self._slots * self._chunk_steps
        with self._lock:
            self._append_locked("idle", 0, total)
            self._passes += 1

    def note_tokens(self, n: int) -> None:
        """``n`` tokens harvested (the achieved-throughput numerator)."""
        with self._lock:
            self._tokens += int(n)

    # -- request hooks (from the harvester's finish path) ------------------

    def observe_request(self, ttft_ms: float, itl_mean_ms: float) -> None:
        """Feed one completed request's TTFT and mean ITL into the
        regression watchdog (ITL only when the request decoded more
        than its first token)."""
        self.watchdog.observe_ttft(ttft_ms)
        if itl_mean_ms > 0.0:
            self.watchdog.observe_itl(itl_mean_ms)

    # -- reporting ---------------------------------------------------------

    def _append_locked(self, kind, occ, total) -> None:
        # deque(maxlen) evicts silently on append, which would desync
        # the running sums — pop the victim explicitly first
        if len(self._ring) == self._ring.maxlen:
            k0, o0, t0 = self._ring.popleft()
            if k0 == "idle":
                self._idle_steps -= t0
            else:
                self._occ_steps -= o0
                self._disp_steps -= t0
        self._ring.append((kind, occ, total))
        if kind == "idle":
            self._idle_steps += total
        else:
            self._occ_steps += occ
            self._disp_steps += total

    def _ratios_locked(self):
        occ = self._occ_steps
        disp = self._disp_steps
        idle = self._idle_steps
        goodput = occ / (disp + idle) if (disp + idle) else 0.0
        occupancy = occ / disp if disp else 0.0
        return goodput, occupancy, self._kv_pressure

    def _sample_ratios(self):
        with self._lock:
            return self._ratios_locked()

    def report(self) -> dict:
        """The ``/debug/goodput`` body for this engine: ring
        classification counts + slot-step sums, the three ratios,
        achieved tokens/s since construction (or :meth:`reset`), and
        the watchdog advisory."""
        with self._lock:
            ring = list(self._ring)
            passes = self._passes
            tokens = self._tokens
            elapsed = max(1e-9, self._clock() - self._t0)
            ratios = self._ratios_locked()
        counts = {kind: 0 for kind in PASS_KINDS}
        slot_steps = {kind: 0 for kind in PASS_KINDS}
        occupied = 0
        for kind, occ, total in ring:
            counts[kind] += 1
            slot_steps[kind] += total
            occupied += occ
        goodput, occupancy, pressure = ratios
        return {
            "engine": self._engine,
            "phase": self._phase,
            "slots": self._slots,
            "chunk_steps": self._chunk_steps,
            "ring_passes": len(ring),
            "total_passes": passes,
            "passes": counts,
            "slot_steps": slot_steps,
            "occupied_slot_steps": occupied,
            "goodput_ratio": round(goodput, 6),
            "occupancy_ratio": round(occupancy, 6),
            "kv_pressure_ratio": round(pressure, 6),
            "tokens": tokens,
            "tokens_per_s": round(tokens / elapsed, 3),
            "watchdog": self.watchdog.advisory(),
        }

    def reset(self) -> None:
        """Clear the ring and re-anchor the throughput window (the
        windowed ``stats()``/bench reset path)."""
        with self._lock:
            self._ring.clear()
            self._occ_steps = 0
            self._disp_steps = 0
            self._idle_steps = 0
            self._passes = 0
            self._tokens = 0
            self._t0 = self._clock()
            self._kv_pressure = 0.0
