"""Per-tenant usage metering: request-level cost attribution ledger.

ROADMAP item 4 (multi-tenant adapter serving) needs per-tenant fairness
and quotas, but nothing in the stack could previously say *what a
request costs*: PR 4's cost analysis is per-program, the pool telemetry
is global, and trace spans time requests without attributing shared
device work — one decode chunk advances every resident slot at once, so
"this tenant's chunk" is not a thing the hardware knows. This module is
the measurement substrate (S-LoRA / VTC-style fair serving presupposes
per-client token/compute accounting): a :class:`UsageLedger` that
assembles, per request, a **resource vector** —

- ``queue_ms`` — submit-to-admission wait,
- ``prefill_tokens`` / ``cached_tokens`` — prompt tokens actually
  prefilled vs. spliced from the prefix cache (the savings are credited
  to the tenant HOLDING the lease, i.e. the one that reused the rows),
- ``decode_tokens`` — tokens served,
- ``device_seconds`` / ``flops`` — each dispatched program's cost (wall
  between consecutive harvests, the :class:`~unionml_tpu.introspection
  .ProgramTracker` cost-analysis FLOPs) split across the live occupants
  of the batch/chunk, **weighted by their harvested-token share**,
- ``kv_block_seconds`` — block-seconds integrated over
  :class:`~unionml_tpu.serving.kv_pool.KVBlockPool` hold times (paged
  engines; freed on retirement, abandon, and recovery alike).

Tenant identity flows end to end: the transports accept an
``X-Tenant-ID`` header (validated — see :func:`validate_tenant` — and
echoed on every response), open a :func:`tenant_scope` around the
predictor call the same way deadlines and trace contexts propagate, and
the engine/batcher pick it up at submission via :func:`current_tenant`
(default ``anonymous``).

**Cardinality policy.** Tenant ids are request-derived and therefore
unbounded; metric label values must not be. The ledger exports
``unionml_tenant_*`` series through a **bounded rollup**: the first
``top_k`` distinct tenants that record usage get dedicated label values
(heavy tenants recur and claim their slot on first contact — the
Misra-Gries/space-saving property for never-decremented counters), and
every later tenant lands in the single ``other`` label. Assignment is
sticky, so counters stay monotonic; total exported tenant-label
cardinality is at most ``top_k + 1`` regardless of distinct tenant
count. Exact per-tenant vectors (up to ``max_tenants``, then an
``other`` accumulator) are served at ``GET /debug/usage`` — JSON, not
label values, so the debug surface can afford precision the metric
surface cannot. ``scripts/lint_basics.py`` enforces that no other
module registers a ``unionml_*`` series with a request-derived label.

The ledger is the off-switchable seam: engines and batchers built
without one (``usage=None``, the default) pay a single attr-is-None
check per record site.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, Iterator, Optional

from unionml_tpu import telemetry

__all__ = [
    "DEFAULT_TENANT",
    "MAX_TENANT_LEN",
    "OTHER_TENANT",
    "UsageLedger",
    "current_tenant",
    "tenant_scope",
    "validate_tenant",
]

DEFAULT_TENANT = "anonymous"
OTHER_TENANT = "other"
MAX_TENANT_LEN = 64

# drop causes are a CLOSED set (metric label values): free-form error
# detail belongs in the flight recorder, not in label cardinality
DROP_CAUSES = ("abandoned", "deadline_shed", "error")


def validate_tenant(value: Optional[str]) -> str:
    """Normalize a tenant id: ``None``/empty → :data:`DEFAULT_TENANT`;
    values longer than :data:`MAX_TENANT_LEN` or containing
    non-printable characters raise ``ValueError`` (the transports map it
    to 422) — a hostile header must be rejected at the boundary, never
    minted into a label value or a ledger key."""
    if value is None or value == "":
        return DEFAULT_TENANT
    tenant = str(value)
    if len(tenant) > MAX_TENANT_LEN:
        raise ValueError(
            f"tenant id longer than {MAX_TENANT_LEN} chars "
            f"({len(tenant)}): set a stable short identifier in "
            "X-Tenant-ID"
        )
    if not tenant.isprintable():
        raise ValueError(
            "tenant id contains non-printable characters: X-Tenant-ID "
            "must be printable text"
        )
    return tenant


_tenant_tls = threading.local()


@contextmanager
def tenant_scope(tenant: Optional[str]) -> Iterator[None]:
    """Expose ``tenant`` to engine/batcher submissions on this thread
    (``None`` leaves any outer scope visible). The transports open this
    around the predictor call — deadline-scope-style thread-local
    plumbing, so no predictor wrapper threads a tenant kwarg through."""
    if tenant is None:
        yield
        return
    prev = getattr(_tenant_tls, "tenant", None)
    _tenant_tls.tenant = tenant
    try:
        yield
    finally:
        _tenant_tls.tenant = prev


def current_tenant() -> str:
    """The innermost :func:`tenant_scope` tenant on this thread, else
    :data:`DEFAULT_TENANT`."""
    tenant = getattr(_tenant_tls, "tenant", None)
    return tenant if tenant else DEFAULT_TENANT


class _TenantUsage:
    """One tenant's exact cumulative resource vector (ledger lock)."""

    __slots__ = (
        "requests", "queue_ms", "prefill_tokens", "cached_tokens",
        "decode_tokens", "device_seconds", "flops", "kv_block_seconds",
        "rejected", "deadline_shed", "dropped", "by_priority",
        "by_phase", "by_version",
    )

    def __init__(self):
        self.by_priority: Dict[str, int] = {}
        self.by_phase: Dict[str, int] = {}
        self.by_version: Dict[str, int] = {}
        self.requests = 0
        self.queue_ms = 0.0
        self.prefill_tokens = 0
        self.cached_tokens = 0
        self.decode_tokens = 0
        self.device_seconds = 0.0
        self.flops = 0.0
        self.kv_block_seconds = 0.0
        self.rejected = 0
        self.deadline_shed = 0
        self.dropped = 0

    def vector(self) -> dict:
        return {
            "requests": self.requests,
            # priority breakdown of completed requests (closed value
            # set — scheduler.PRIORITIES — so JSON keys stay bounded;
            # kept out of the metric surface: the per-tenant label
            # cardinality budget is spent)
            "requests_by_priority": dict(self.by_priority),
            # serving-phase breakdown (closed set — scheduler.PHASES):
            # on a disaggregated fleet the prefill pool's 1-token legs
            # and the decode pool's streams are separately countable
            # per tenant (JSON-only, same cardinality argument)
            "requests_by_phase": dict(self.by_phase),
            # model-version breakdown (slug-validated registry ids —
            # a fleet serves at most live + canary during a rollout,
            # so the key set stays bounded; JSON-only like the
            # others): during a canary bake a tenant's bill is
            # splittable by which weights answered
            "requests_by_version": dict(self.by_version),
            "queue_ms": round(self.queue_ms, 3),
            "prefill_tokens": self.prefill_tokens,
            "cached_tokens": self.cached_tokens,
            "decode_tokens": self.decode_tokens,
            "device_seconds": round(self.device_seconds, 9),
            "flops": self.flops,
            "kv_block_seconds": round(self.kv_block_seconds, 9),
            "rejected": self.rejected,
            "deadline_shed": self.deadline_shed,
            "dropped": self.dropped,
        }


class UsageLedger:
    """Request-level cost attribution with bounded-cardinality export.

    One ledger per serving surface (share it between an engine and the
    :class:`~unionml_tpu.serving.http.ServingApp` serving its
    ``/debug/usage``); engines/batchers record into it at admission,
    harvest, and retirement. Thread-safe — the engine calls some sites
    with its own lock held, so the ledger must never call back into
    engine state (it never does: pure accumulation).

    Args:
        registry: explicit :class:`~unionml_tpu.telemetry
            .MetricsRegistry`; defaults to the process-global one.
        top_k: dedicated tenant label slots. Exported
            ``unionml_tenant_*`` cardinality is at most ``top_k + 1``
            (the ``other`` rollup) no matter how many distinct tenants
            appear. Sticky first-contact assignment keeps every series
            monotonic.
        max_tenants: the ledger's host-memory bound, independent of
            the label bound: exact per-tenant vectors tracked for
            ``/debug/usage`` (tenants past the cap accumulate into the
            ``other`` vector), and the cap on remembered tenant ids —
            past it, unseen tenants resolve to the ``other`` label
            without being stored, so a client minting a fresh id per
            request cannot grow memory or the debug body unboundedly.
    """

    def __init__(
        self,
        *,
        registry: Optional[telemetry.MetricsRegistry] = None,
        top_k: int = 8,
        max_tenants: int = 1024,
    ):
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if max_tenants < top_k:
            raise ValueError(
                f"max_tenants {max_tenants} must be >= top_k {top_k}"
            )
        self.top_k = int(top_k)
        self.max_tenants = int(max_tenants)
        self._registry = (
            registry if registry is not None else telemetry.get_registry()
        )
        self.instance = telemetry.instance_label("usage")
        self._lock = threading.Lock()
        self._tenants: Dict[str, _TenantUsage] = {}
        self._other = _TenantUsage()       # tenants past max_tenants
        # tenant -> exported label, bounded at max_tenants entries: a
        # client minting a fresh (valid) tenant id per request must not
        # grow host memory without bound, so past the cap unseen
        # tenants resolve to `other` WITHOUT being remembered
        self._labels: Dict[str, str] = {}
        self._dedicated = 0                # label slots assigned (<= top_k)
        self._distinct = 0                 # distinct tenants tracked
        # engine-side totals (the attribution-identity denominator):
        # ALL dispatched work, attributed or not — a chunk harvested
        # with no live owner still burned device time
        self.total_device_seconds = 0.0
        self.total_flops = 0.0
        self.total_tokens = 0
        self._capacity_slot_steps = 0.0
        self._used_slot_steps: Dict[str, float] = {}
        # per-label resolved (decode, device_s, flops) counter children:
        # attribute() runs on the harvester thread once per dispatched
        # chunk, so the family .labels() tuple-hash + lock is cached
        # away (the serve_usage bench holds the overhead bar at <= 2%)
        self._attr_children: Dict[str, tuple] = {}
        self._build_instruments()

    # ------------------------------------------------------------------ #
    # metric families (the ONE home for request-derived labels — the
    # lint_basics cardinality guard exempts exactly this module)
    # ------------------------------------------------------------------ #

    def _build_instruments(self) -> None:
        R, lbl = self._registry, ("ledger", "tenant")

        def counter(name, help):
            return R.counter(name, help, lbl)

        self._f_requests = counter(
            "unionml_tenant_requests_total",
            "Completed requests per tenant (bounded top-K rollup: "
            "tenants past the ledger's label slots report as 'other').",
        )
        self._f_queue_ms = counter(
            "unionml_tenant_queue_ms_total",
            "Submit-to-admission wait milliseconds per tenant.",
        )
        self._f_prefill = counter(
            "unionml_tenant_prefill_tokens_total",
            "Prompt tokens actually prefilled per tenant.",
        )
        self._f_cached = counter(
            "unionml_tenant_cached_tokens_total",
            "Prompt tokens spliced from the prefix cache per tenant "
            "(prefill work saved, credited to the leasing tenant).",
        )
        self._f_decode = counter(
            "unionml_tenant_decode_tokens_total",
            "Tokens served per tenant (batcher ledgers count rows).",
        )
        self._f_device_s = counter(
            "unionml_tenant_device_seconds_total",
            "Attributed device-seconds per tenant: each dispatch's "
            "cost split across the live batch occupants by harvested-"
            "token share.",
        )
        self._f_flops = counter(
            "unionml_tenant_flops_total",
            "Attributed FLOPs per tenant (ProgramTracker cost analysis "
            "split by token share; 0 when introspection is off).",
        )
        self._f_kv_s = counter(
            "unionml_tenant_kv_block_seconds_total",
            "KV block-seconds per tenant: pool-block hold time "
            "integrated from take to release (retire/abandon/recovery).",
        )
        self._f_rejected = R.counter(
            "unionml_tenant_rejected_total",
            "Admission-control rejections per tenant and reason.",
            ("ledger", "tenant", "reason"),
        )
        self._f_shed = counter(
            "unionml_tenant_deadline_shed_total",
            "Requests shed at dequeue per tenant (deadline expired "
            "before prefill).",
        )
        self._f_dropped = R.counter(
            "unionml_tenant_dropped_total",
            "Requests dropped mid-flight per tenant and cause "
            "(abandoned / deadline_shed / error).",
            ("ledger", "tenant", "cause"),
        )
        self._g_capacity = R.gauge(
            "unionml_tenant_capacity_fraction",
            "Fraction of decode slot-step capacity a tenant consumed "
            "since the last reset (headroom = 1 - sum over tenants).",
            ("ledger", "tenant"),
        )
        self._g_distinct = R.gauge(
            "unionml_tenant_distinct",
            "Distinct tenant ids tracked by this ledger (saturates at "
            "max_tenants — the host-memory bound; label cardinality "
            "stays top_k + 1 regardless).",
            ("ledger",),
        ).labels(self.instance)

    # ------------------------------------------------------------------ #
    # rollup
    # ------------------------------------------------------------------ #

    def label_for(self, tenant: str) -> str:
        """The exported label value for ``tenant``: a dedicated slot
        for the first ``top_k`` distinct tenants (sticky — counters
        must stay monotonic), :data:`OTHER_TENANT` for everyone else.
        The bounded-rollup helper every ``unionml_tenant_*`` increment
        routes through."""
        with self._lock:
            return self._label_locked(tenant)

    def _label_locked(self, tenant: str) -> str:
        label = self._labels.get(tenant)
        if label is None:
            if self._dedicated < self.top_k and tenant != OTHER_TENANT:
                label = tenant
                self._dedicated += 1
            else:
                label = OTHER_TENANT
                if len(self._labels) >= self.max_tenants:
                    # past the memory bound: resolve without remembering
                    return label
            self._labels[tenant] = label
            self._distinct += 1
            self._g_distinct.set(self._distinct)
        return label

    def _acct_locked(self, tenant: str) -> _TenantUsage:
        self._label_locked(tenant)  # seen-tenant bookkeeping
        acct = self._tenants.get(tenant)
        if acct is None:
            if len(self._tenants) >= self.max_tenants:
                return self._other
            acct = _TenantUsage()
            self._tenants[tenant] = acct
        return acct

    # ------------------------------------------------------------------ #
    # recording (engine/batcher call sites)
    # ------------------------------------------------------------------ #

    def finish_request(
        self,
        tenant: str,
        *,
        queue_ms: float = 0.0,
        prefill_tokens: int = 0,
        cached_tokens: int = 0,
        priority: Optional[str] = None,
        phase: Optional[str] = None,
        version: Optional[str] = None,
    ) -> None:
        """One request completed and delivered: the per-request scalars
        (queue wait, prefill split, the scheduling ``priority`` class
        it ran under, the serving ``phase`` of the engine that
        completed it, and the model ``version`` its weights were
        published under) land here; decode tokens and device
        attribution accumulated through :meth:`attribute` as the
        request's chunks harvested."""
        with self._lock:
            label = self._label_locked(tenant)
            acct = self._acct_locked(tenant)
            acct.requests += 1
            acct.queue_ms += queue_ms
            acct.prefill_tokens += int(prefill_tokens)
            acct.cached_tokens += int(cached_tokens)
            if priority is not None:
                acct.by_priority[priority] = (
                    acct.by_priority.get(priority, 0) + 1
                )
            if phase is not None:
                acct.by_phase[phase] = acct.by_phase.get(phase, 0) + 1
            if version is not None:
                acct.by_version[version] = (
                    acct.by_version.get(version, 0) + 1
                )
        lbl = (self.instance, label)
        self._f_requests.labels(*lbl).inc()
        if queue_ms > 0:
            self._f_queue_ms.labels(*lbl).inc(queue_ms)
        if prefill_tokens:
            self._f_prefill.labels(*lbl).inc(int(prefill_tokens))
        if cached_tokens:
            self._f_cached.labels(*lbl).inc(int(cached_tokens))

    def attribute(
        self,
        tenant_tokens: Dict[str, int],
        *,
        device_s: float = 0.0,
        flops: float = 0.0,
        slot_steps: float = 0.0,
    ) -> None:
        """Attribute one dispatch (a decode chunk, a prefill, a batched
        device call): ``device_s`` and ``flops`` split across
        ``tenant_tokens`` weighted by token share; each tenant's tokens
        credit its ``decode_tokens``. Totals accumulate UNATTRIBUTED
        (a chunk whose every occupant went stale still burned device
        time — the identity check's honest denominator).
        ``slot_steps`` grows the capacity denominator for the headroom
        estimate (``chunk_steps * slots`` per decode chunk)."""
        device_s = max(0.0, float(device_s))
        flops = max(0.0, float(flops))
        slot_steps = max(0.0, float(slot_steps))
        total_tokens = sum(tenant_tokens.values())
        shares = []
        with self._lock:
            self.total_device_seconds += device_s
            self.total_flops += flops
            self.total_tokens += total_tokens
            self._capacity_slot_steps += slot_steps
            for tenant, tokens in tenant_tokens.items():
                if tokens <= 0:
                    continue
                w = tokens / total_tokens
                acct = self._acct_locked(tenant)
                acct.decode_tokens += int(tokens)
                acct.device_seconds += device_s * w
                acct.flops += flops * w
                if slot_steps > 0:
                    # only capacity-bearing dispatches (decode chunks)
                    # count as used slot-steps — a prefill's sampled
                    # token or a batcher row is not decode capacity;
                    # untracked tenants roll into the `other` key so
                    # the dict stays max_tenants-bounded
                    key = (
                        tenant if acct is not self._other
                        else OTHER_TENANT
                    )
                    self._used_slot_steps[key] = (
                        self._used_slot_steps.get(key, 0.0) + tokens
                    )
                shares.append(
                    (self._label_locked(tenant), tokens, w)
                )
        for label, tokens, w in shares:
            children = self._attr_children.get(label)
            if children is None:
                lbl = (self.instance, label)
                children = (
                    self._f_decode.labels(*lbl),
                    self._f_device_s.labels(*lbl),
                    self._f_flops.labels(*lbl),
                )
                self._attr_children[label] = children
            children[0].inc(tokens)
            if device_s:
                children[1].inc(device_s * w)
            if flops:
                children[2].inc(flops * w)

    def record_kv_block_seconds(self, tenant: str, seconds: float) -> None:
        """Integrate one request's pool-block hold time (taken → freed;
        the engine calls this on retirement, abandon-drop, AND recovery,
        so no hold window is ever left open)."""
        seconds = max(0.0, float(seconds))
        if seconds == 0.0:
            return
        with self._lock:
            label = self._label_locked(tenant)
            self._acct_locked(tenant).kv_block_seconds += seconds
        self._f_kv_s.labels(self.instance, label).inc(seconds)

    def record_rejected(
        self, tenant: str, reason: str, n: int = 1
    ) -> None:
        """Admission-control rejection (reason is the engine/batcher's
        closed reason set: queue_full / breaker_open / draining /
        pool_full) — overload postmortems can name who was shed."""
        with self._lock:
            label = self._label_locked(tenant)
            self._acct_locked(tenant).rejected += n
        self._f_rejected.labels(self.instance, label, reason).inc(n)

    def record_deadline_shed(self, tenant: str) -> None:
        with self._lock:
            label = self._label_locked(tenant)
            self._acct_locked(tenant).deadline_shed += 1
        self._f_shed.labels(self.instance, label).inc()

    def record_drop(self, tenant: str, cause: str) -> None:
        """A request failed mid-flight. ``cause`` outside the closed
        :data:`DROP_CAUSES` set (free-form error detail) reports as
        ``error`` — detail belongs in the flight recorder, not in label
        cardinality."""
        if cause not in DROP_CAUSES:
            cause = "error"
        with self._lock:
            label = self._label_locked(tenant)
            self._acct_locked(tenant).dropped += 1
        self._f_dropped.labels(self.instance, label, cause).inc()

    def fair_share(self, tenant: str) -> float:
        """``tenant``'s fraction of ATTRIBUTED device-seconds so far
        (0.0 when nothing is attributed yet or the tenant is unknown)
        — the cheap read the preemptive scheduler's deficit queues
        scale their refill quanta by (a tenant that already consumed
        most of the device refills slower, so its class's light users
        catch up). Tenants rolled past ``max_tenants`` share the
        ``other`` accumulator's vector and therefore its share."""
        with self._lock:
            acct = self._tenants.get(tenant)
            if acct is None and len(self._tenants) >= self.max_tenants:
                acct = self._other
            if acct is None or self.total_device_seconds <= 0.0:
                return 0.0
            return min(
                1.0, acct.device_seconds / self.total_device_seconds
            )

    def capacity_totals(self) -> "tuple[float, float]":
        """``(capacity_slot_steps, used_slot_steps)`` — the raw decode
        capacity counters, cumulative since the last reset. A cheap
        read (one lock, no gauge refresh, no report assembly) for
        pollers that difference consecutive samples into a *windowed*
        utilization — the autoscaler's headroom signal works on deltas
        between evaluations, so an idle morning never dilutes an
        overloaded afternoon (docs/robustness.md "Autoscaling &
        self-healing")."""
        with self._lock:
            return (
                self._capacity_slot_steps,
                sum(self._used_slot_steps.values()),
            )

    def capacity_headroom(self) -> float:
        """``1 - used/capacity`` over everything since the last reset
        (1.0 with no capacity dispatched) — the cumulative convenience
        read; pollers that need recency should difference
        :meth:`capacity_totals` instead."""
        cap, used = self.capacity_totals()
        if cap <= 0.0:
            return 1.0
        return max(0.0, 1.0 - used / cap)

    # ------------------------------------------------------------------ #
    # views
    # ------------------------------------------------------------------ #

    def _capacity_locked(self) -> dict:
        cap = self._capacity_slot_steps
        fractions = {
            tenant: used / cap if cap > 0 else 0.0
            for tenant, used in self._used_slot_steps.items()
        }
        return {
            "slot_steps": cap,
            "per_tenant": {
                t: round(f, 4) for t, f in sorted(
                    fractions.items(), key=lambda kv: -kv[1]
                )
            },
            "headroom": round(
                max(0.0, 1.0 - sum(fractions.values())), 4
            ),
        }

    def report(self) -> dict:
        """The ``GET /debug/usage`` body: exact per-tenant resource
        vectors (every tracked tenant — JSON can afford what label
        cardinality cannot), the attribution-identity totals, cache
        savings, and the decode capacity-headroom estimate. Also
        refreshes the ``unionml_tenant_capacity_fraction`` gauges."""
        with self._lock:
            tenants = {
                t: acct.vector() for t, acct in sorted(
                    self._tenants.items(),
                    key=lambda kv: -kv[1].device_seconds,
                )
            }
            other = self._other.vector()
            capacity = self._capacity_locked()
            labels = dict(self._labels)
            distinct = self._distinct
            totals = {
                "device_seconds": round(self.total_device_seconds, 9),
                "flops": self.total_flops,
                "tokens": self.total_tokens,
            }
        attributed_s = sum(v["device_seconds"] for v in tenants.values())
        attributed_s += other["device_seconds"]
        attributed_tok = sum(v["decode_tokens"] for v in tenants.values())
        attributed_tok += other["decode_tokens"]
        saved = sum(v["cached_tokens"] for v in tenants.values())
        saved += other["cached_tokens"]
        # gauge export aggregates by LABEL: several rolled-up tenants
        # share the `other` series, so their fractions must sum (a
        # per-tenant set() would leave one arbitrary tenant's value)
        by_label: Dict[str, float] = {}
        for tenant, frac in capacity["per_tenant"].items():
            label = labels.get(tenant, OTHER_TENANT)
            by_label[label] = by_label.get(label, 0.0) + frac
        for label, frac in by_label.items():
            self._g_capacity.labels(self.instance, label).set(frac)
        return {
            "ledger": self.instance,
            "top_k": self.top_k,
            "distinct_tenants": distinct,
            "exported_labels": sorted(set(labels.values())),
            "tenants": tenants,
            "other": other,
            "totals": totals,
            "attribution": {
                "attributed_device_seconds": round(attributed_s, 9),
                "attributed_tokens": attributed_tok,
                "device_seconds_coverage": round(
                    attributed_s / totals["device_seconds"], 4
                ) if totals["device_seconds"] else 1.0,
                "token_coverage": round(
                    attributed_tok / totals["tokens"], 4
                ) if totals["tokens"] else 1.0,
            },
            "cache_savings_tokens": saved,
            "capacity": capacity,
        }

    def stats(self) -> dict:
        """The compact ``stats()["usage"]`` section (the full report is
        ``GET /debug/usage``)."""
        report = self.report()
        return {
            "distinct_tenants": report["distinct_tenants"],
            "exported_labels": report["exported_labels"],
            "totals": report["totals"],
            "attribution": report["attribution"],
            "cache_savings_tokens": report["cache_savings_tokens"],
            "capacity_headroom": report["capacity"]["headroom"],
        }

    def reset_stats(self) -> None:
        """Zero vectors, totals, and this ledger's series (benchmarks
        call this between phases). Label-slot assignments are KEPT —
        they describe exported series that still exist, and re-assigning
        them would un-stick the rollup."""
        with self._lock:
            self._tenants.clear()
            self._other = _TenantUsage()
            self.total_device_seconds = 0.0
            self.total_flops = 0.0
            self.total_tokens = 0
            self._capacity_slot_steps = 0.0
            self._used_slot_steps.clear()
        for family in (
            self._f_requests, self._f_queue_ms, self._f_prefill,
            self._f_cached, self._f_decode, self._f_device_s,
            self._f_flops, self._f_kv_s, self._f_rejected, self._f_shed,
            self._f_dropped, self._g_capacity,
        ):
            family.reset()
