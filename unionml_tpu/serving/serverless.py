"""Serverless serving adapters: gateway events and object-store events.

Reference parity for the two AWS-flavored serving templates
(reference: templates/basic-aws-lambda — FastAPI wrapped in Mangum for
API-Gateway events, docs/source/serving_aws_lambda.md:40-56 — and
templates/basic-aws-lambda-s3 — S3-event-driven batch prediction,
docs/source/reacting_to_s3_events.md:38-50). Instead of depending on
Mangum/boto3, the adapters speak the event *shapes* directly and route to
the transport-agnostic :class:`~unionml_tpu.serving.http.ServingApp`:

- :func:`gateway_handler` — API-Gateway-style ``{httpMethod, path, body}``
  events → ``{statusCode, headers, body}`` responses (GET /,
  GET /health with the non-ok→503 readiness contract, GET /stats,
  Prometheus GET /metrics, POST /predict with the shared
  429/503/504 fault mapping and ``X-Deadline-Ms`` propagation; every
  response carries ``X-Request-ID``). Works as an AWS Lambda handler
  as-is, with the same serving contract as the HTTP transports.
- :func:`object_event_handler` — S3-style ``{Records: [{s3: {bucket,
  object}}]}`` events: read the uploaded feature file from an
  :class:`ObjectStore`, predict, write ``<key>.predictions.json`` back.
  ``LocalObjectStore`` maps bucket/key onto a directory for tests and
  on-prem use; a boto3-backed store can be swapped in without touching
  the handler.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Callable, Dict, Optional
from urllib.parse import unquote_plus

from unionml_tpu import telemetry
from unionml_tpu.serving.faults import (
    DeadlineExceeded,
    EngineUnavailable,
    Overloaded,
    deadline_scope,
    http_fault_response,
    parse_deadline_header,
)
from unionml_tpu.serving.http import ServingApp
from unionml_tpu.serving.scheduler import (
    DEFAULT_MODEL_VERSION,
    DEFAULT_PRIORITY,
    model_version_scope,
    priority_scope,
    validate_model_version,
    validate_priority,
)
from unionml_tpu.serving.usage import (
    DEFAULT_TENANT,
    tenant_scope,
    validate_tenant,
)


class ObjectStore:
    """Minimal bucket/key object interface the event handler needs."""

    def get(self, bucket: str, key: str) -> bytes:
        raise NotImplementedError

    def put(self, bucket: str, key: str, data: bytes) -> None:
        raise NotImplementedError


class LocalObjectStore(ObjectStore):
    """Directory-backed store: ``root/bucket/key``.

    Bucket/key come from untrusted event payloads, so every path is
    resolved and checked to stay under ``root`` (no traversal via
    ``../`` or absolute keys).
    """

    def __init__(self, root: str):
        self.root = Path(root).resolve()

    def _path(self, bucket: str, key: str) -> Path:
        path = (self.root / bucket / key).resolve()
        if not path.is_relative_to(self.root):
            raise ValueError(f"object path escapes store root: {bucket!r}/{key!r}")
        return path

    def get(self, bucket: str, key: str) -> bytes:
        return self._path(bucket, key).read_bytes()

    def put(self, bucket: str, key: str, data: bytes) -> None:
        path = self._path(bucket, key)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_bytes(data)


def _event_headers(event: Dict[str, Any]) -> Dict[str, str]:
    """Case-folded request headers from a gateway event (API-Gateway
    forwards client headers lowercased in v2 events, mixed-case in v1)."""
    raw = event.get("headers") or {}
    return {str(k).lower(): str(v) for k, v in raw.items()}


def gateway_handler(
    model,
    *,
    batch: bool = False,
    **serving_kwargs,
) -> Callable[[Dict[str, Any], Any], Dict[str, Any]]:
    """Build a ``handler(event, context)`` for API-Gateway-style events.

    Same serving contract as the HTTP transports
    (:mod:`unionml_tpu.serving.http` / ``fastapi``):

    - ``GET /metrics`` — Prometheus exposition of the app's registry,
    - ``GET /debug/trace?format=chrome|jsonl``, ``GET /debug/slo``,
      and ``GET /debug/usage`` — the trace export, SLO burn-rate
      report, and per-tenant usage report, same contract as the HTTP
      transports,
    - tenant identity: an ``X-Tenant-ID`` request header is validated
      (over 64 chars / non-printable → **422**, default
      ``anonymous``), echoed on every response, and scoped around
      ``POST /predict`` so engine/batcher usage ledgers bill the
      request's resource vector to it,
    - every response carries ``X-Request-ID`` (the incoming header is
      echoed when the gateway forwarded one, else a fresh id is
      minted) and lands in the ``transport="serverless"`` request
      series,
    - W3C trace propagation: an inbound ``traceparent`` header is
      parsed (a root is minted when absent/malformed), ``POST
      /predict`` opens the shared
      :meth:`~unionml_tpu.serving.http.ServingApp.traced_request`
      timeline so engine/batcher spans join the caller's trace, and
      every response echoes a ``traceparent``,
    - ``GET /health`` answers **503** for any non-``ok`` status
      (draining / circuit breaker), so gateway health checks stop
      routing here,
    - typed serving faults map to the shared HTTP contract:
      ``Overloaded`` → 429 + ``Retry-After``, ``EngineUnavailable`` →
      503 + ``Retry-After``, ``DeadlineExceeded`` → 504; an
      ``X-Deadline-Ms`` request header opens the same
      :func:`~unionml_tpu.serving.faults.deadline_scope`,
    - validation errors answer **422** (parity with both HTTP
      transports; this was 400 before the contract was unified).
    """
    app = ServingApp(model, batch=batch, **serving_kwargs)

    def handler(event: Dict[str, Any], context: Any = None) -> Dict[str, Any]:
        method = (event.get("httpMethod") or event.get("requestContext", {})
                  .get("http", {}).get("method", "GET")).upper()
        path = event.get("path") or event.get("rawPath") or "/"
        headers = _event_headers(event)
        rid = headers.get("x-request-id") or telemetry.new_request_id()
        raw_traceparent = headers.get("traceparent")
        # echoed on every response; /predict swaps in its recorded
        # server-span context below so callers stitch the full tree
        trace_ctx = telemetry.server_trace_context(raw_traceparent)
        tenant = DEFAULT_TENANT
        priority = DEFAULT_PRIORITY
        model_version = DEFAULT_MODEL_VERSION
        t0 = time.perf_counter()

        def respond(
            status: int, body: str, content_type: str = "application/json",
            extra: Optional[Dict[str, str]] = None,
        ) -> Dict[str, Any]:
            app.observe_request(
                "serverless", path, status,
                (time.perf_counter() - t0) * 1e3,
            )
            return {
                "statusCode": status,
                "headers": {
                    "Content-Type": content_type,
                    "X-Request-ID": rid,
                    "X-Tenant-ID": tenant,
                    "X-Priority": priority,
                    "X-Model-Version": model_version,
                    "traceparent": telemetry.format_traceparent(trace_ctx),
                    **(extra or {}),
                },
                "body": body,
            }

        try:
            # validated at the boundary (422 via the ValueError arm
            # below), echoed on every response like the HTTP transports
            tenant = validate_tenant(headers.get("x-tenant-id"))
            priority = validate_priority(headers.get("x-priority"))
            model_version = validate_model_version(
                headers.get("x-model-version")
            )
            if method == "GET" and path == "/":
                return respond(200, app.root(), content_type="text/html")
            if method == "GET" and path == "/health":
                h = app.health()
                # non-ok => 503, the readiness contract the HTTP
                # transports already serve (docs/robustness.md)
                return respond(app.health_status(h), json.dumps(h))
            if method == "GET" and path == "/stats":
                return respond(200, json.dumps(app.stats()))
            if method == "GET" and path == "/metrics":
                return respond(
                    200, app.metrics_text(),
                    content_type=telemetry.EXPOSITION_CONTENT_TYPE,
                )
            if method == "GET" and path == "/debug/trace":
                qs = event.get("queryStringParameters") or {}
                body_out, content_type = app.debug_trace(
                    qs.get("format", "chrome"),
                    rid=qs.get("rid"), trace=qs.get("trace"),
                )
                if not isinstance(body_out, str):
                    body_out = json.dumps(body_out)
                return respond(200, body_out, content_type=content_type)
            if method == "GET" and path == "/debug/slo":
                return respond(200, json.dumps(app.debug_slo()))
            if method == "GET" and path == "/debug/usage":
                return respond(200, json.dumps(app.debug_usage()))
            if method == "POST" and path == "/predict":
                payload = json.loads(event.get("body") or "{}")
                deadline_ms = parse_deadline_header(
                    headers.get("x-deadline-ms")
                )
                # keyed by the response X-Request-ID, so
                # /debug/trace?rid= resolves the id the client holds
                with app.traced_request(
                    "/predict", raw_traceparent, rid=rid,
                ) as ctx:
                    trace_ctx = ctx
                    with tenant_scope(tenant):
                        with priority_scope(priority), \
                                model_version_scope(model_version):
                            with deadline_scope(deadline_ms):
                                return respond(
                                    200, json.dumps(app.predict(payload))
                                )
            return respond(
                404, json.dumps({"error": f"no route {method} {path}"})
            )
        except (Overloaded, EngineUnavailable, DeadlineExceeded) as e:
            status, extra = http_fault_response(e)
            body: Dict[str, Any] = {"error": str(e)}
            if isinstance(e, EngineUnavailable):
                body["reason"] = e.reason
            return respond(status, json.dumps(body), extra=extra or None)
        except (ValueError, KeyError, TypeError) as e:
            return respond(422, json.dumps({"error": str(e)}))
        except Exception as e:  # pragma: no cover - defensive 500 surface
            return respond(500, json.dumps({"error": str(e)}))

    handler.serving_app = app  # test/introspection seam
    return handler


def object_event_handler(
    model,
    store: ObjectStore,
    *,
    output_suffix: str = ".predictions.json",
    parse: Optional[Callable[[bytes], Any]] = None,
) -> Callable[[Dict[str, Any], Any], Dict[str, Any]]:
    """Build a ``handler(event, context)`` for S3-style object events.

    For each record, reads the object, runs it through the dataset's
    feature pipeline + predictor, and writes predictions next to the
    input under ``key + output_suffix``.
    """
    app = ServingApp(model)
    parse = parse or (lambda raw: json.loads(raw.decode()))

    def handler(event: Dict[str, Any], context: Any = None) -> Dict[str, Any]:
        outputs = []
        errors = []
        for record in event.get("Records", []):
            s3 = record.get("s3", {})
            bucket = s3.get("bucket", {}).get("name")
            key = s3.get("object", {}).get("key")
            if not bucket or not key:
                continue
            # real S3 notifications URL-encode keys ("my file" -> "my+file")
            key = unquote_plus(key)
            try:
                features = parse(store.get(bucket, key))
                preds = app.predict({"features": features})
                out_key = key + output_suffix
                # predict() output is already JSON-safe (ServingApp contract)
                store.put(bucket, out_key, json.dumps(preds).encode())
                outputs.append({"bucket": bucket, "key": out_key})
            except Exception as e:
                # one bad object must not abort the batch: report it and
                # keep the already-written outputs visible to the caller
                errors.append({"bucket": bucket, "key": key, "error": str(e)})
        body = {"outputs": outputs}
        if errors:
            body["errors"] = errors
        return {"statusCode": 200 if not errors else 207, "body": json.dumps(body)}

    handler.serving_app = app
    return handler
