"""Block-paged device KV pool: the host-side free-list allocator.

The contiguous engine reserves ``cache_len`` rows of device KV per slot
— a short prompt routed into a long bucket strands the bucket's full
padding in HBM, and the slot count (the effective batch size) is capped
by the WORST-case sequence, not the traffic actually served. The paged
layout (PagedAttention lineage — Kwon et al., SOSP 2023) breaks that
coupling: device KV lives in one global pool of fixed-size blocks
(``block_size`` tokens each, per layer ``[num_blocks, block_size,
kv_heads, head_dim]``), each resident slot holds an int32 **block
table** mapping its logical rows to pool blocks, and a sequence's table
grows one block at a time as decode proceeds — so HBM is charged for
tokens actually materialized, not for bucket padding.

This module is the host half: a thread-compatible free-list allocator
(callers synchronize — the engine serializes access under its own lock,
matching the dispatcher/harvester split) with **reservation** semantics:
admission reserves a request's worst-case block count up front
(``ceil((prompt + max_new_tokens) / block_size)``), so mid-decode table
growth can never fail — pool exhaustion surfaces at ADMISSION (a typed
:class:`PoolExhausted` the engine maps to a clean ``Overloaded``/parked
admission), never as a corrupted decode. Block id **0 is the trash
block**: never allocated, it is where the engine routes writes from
retired/overshooting slots, so a recycled block can never be corrupted
by a dead slot's in-flight program.

The device half lives in :class:`~unionml_tpu.serving.engine
.DecodeEngine` (pool state + table-directed scatter/gather programs)
and :mod:`unionml_tpu.ops.paged_attention` (the decode kernel). The
prefix cache (:mod:`unionml_tpu.serving.prefix_cache`) shares the same
``block_size``, so host-store splice and harvest extract are per-block
copies addressed by table entries.

Telemetry (``unionml_kv_pool_*``, per-instance ``pool`` label):

- ``unionml_kv_pool_blocks`` / ``_blocks_in_use`` / ``_blocks_reserved``
  — capacity and live allocation gauges,
- ``unionml_kv_pool_bytes`` — device bytes held by in-use blocks,
- ``unionml_kv_pool_occupancy_ratio`` — (in_use + reserved) / capacity,
- ``unionml_kv_pool_fragmentation_ratio`` — 1 - used rows / (in-use
  blocks x block_size): the internal fragmentation of partially-filled
  tail blocks,
- ``unionml_kv_pool_allocated_blocks_total`` /
  ``_freed_blocks_total`` — flow counters,
- ``unionml_kv_pool_alloc_failures_total`` — reservations refused for
  lack of blocks (the pool-full pressure signal the flight recorder
  pairs with its ``pool_pressure`` events),
- ``unionml_kv_pool_preempted_blocks_total`` — blocks released by
  scheduler preemption (docs/robustness.md "Preemption & fairness"):
  a resident's KV evicted to the host prefix-cache store so a
  higher-priority waiter could admit.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from unionml_tpu import telemetry

__all__ = ["KVBlockPool", "PoolExhausted", "TRASH_BLOCK"]

# block id 0: never allocated; dead/overshooting slots' writes land here
TRASH_BLOCK = 0


class PoolExhausted(Exception):
    """A reservation could not be satisfied: the pool has fewer
    unreserved free blocks than requested. Raised at ADMISSION time
    (reservations make later table growth infallible); the engine maps
    it to a parked admission or a typed ``Overloaded`` reject."""

    def __init__(self, msg: str, *, needed: int = 0, available: int = 0):
        super().__init__(msg)
        self.needed = needed
        self.available = available


class KVBlockPool:
    """Free-list allocator over ``num_blocks`` device KV blocks.

    Args:
        num_blocks: total pool blocks INCLUDING the reserved trash
            block 0 (``capacity == num_blocks - 1`` allocatable) — the
            same count the device pool arrays are built with.
        block_size: tokens per block (shared with the prefix cache).
        block_nbytes: device bytes of one block across every layer and
            buffer — sizes the ``unionml_kv_pool_bytes`` gauge; 0 keeps
            the gauge at 0 (tests without a device pool).
        registry: explicit :class:`~unionml_tpu.telemetry
            .MetricsRegistry`; defaults to the process-global one.

    Not internally locked: the engine owns the synchronization (every
    call site holds the engine lock).
    """

    def __init__(
        self,
        *,
        num_blocks: int,
        block_size: int,
        block_nbytes: int = 0,
        registry: Optional[telemetry.MetricsRegistry] = None,
    ):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the trash block), "
                f"got {num_blocks}"
            )
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.block_nbytes = int(block_nbytes)
        # LIFO free list: recently-freed blocks are re-issued first
        # (their HBM pages are the warmest)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._reserved = 0
        self._used_rows = 0
        # bumped by reset(): ids taken under an older generation are
        # STALE — a late give() from a request that raced the reset
        # must not re-add them (the free list was already rebuilt)
        self.generation = 0
        self._registry = (
            registry if registry is not None else telemetry.get_registry()
        )
        self.instance = telemetry.instance_label("kv_pool")
        self._build_instruments()
        self._sync_gauges()

    # ------------------------------------------------------------------ #
    # telemetry
    # ------------------------------------------------------------------ #

    def _build_instruments(self) -> None:
        R, lbl = self._registry, {"pool": self.instance}

        def gauge(name, help):
            return R.gauge(name, help, ("pool",)).labels(**lbl)

        def counter(name, help):
            return R.counter(name, help, ("pool",)).labels(**lbl)

        self._g_capacity = gauge(
            "unionml_kv_pool_blocks",
            "Allocatable device KV blocks (pool size minus the trash "
            "block).",
        )
        self._g_in_use = gauge(
            "unionml_kv_pool_blocks_in_use",
            "Blocks currently assigned to a slot's block table.",
        )
        self._g_reserved = gauge(
            "unionml_kv_pool_blocks_reserved",
            "Blocks committed to admitted requests but not yet taken "
            "(lazy table growth draws from these).",
        )
        self._g_bytes = gauge(
            "unionml_kv_pool_bytes",
            "Device bytes held by in-use KV blocks.",
        )
        self._g_occupancy = gauge(
            "unionml_kv_pool_occupancy_ratio",
            "(in-use + reserved) blocks / capacity — 1.0 means the next "
            "admission parks or sheds.",
        )
        self._g_frag = gauge(
            "unionml_kv_pool_fragmentation_ratio",
            "1 - used rows / (in-use blocks x block_size): internal "
            "fragmentation of partially-filled tail blocks.",
        )
        self._m_allocated = counter(
            "unionml_kv_pool_allocated_blocks_total",
            "Blocks taken from the free list.",
        )
        self._m_freed = counter(
            "unionml_kv_pool_freed_blocks_total",
            "Blocks returned to the free list.",
        )
        self._m_alloc_failures = counter(
            "unionml_kv_pool_alloc_failures_total",
            "Reservations refused because the pool had too few "
            "unreserved free blocks.",
        )
        self._m_preempted = counter(
            "unionml_kv_pool_preempted_blocks_total",
            "Blocks released by scheduler preemption (a resident's KV "
            "evicted to the host prefix-cache store; the blocks return "
            "to the free list once the dispatch fence passes).",
        )

    def _sync_gauges(self) -> None:
        cap = self.capacity
        in_use = self.in_use
        self._g_capacity.set(cap)
        self._g_in_use.set(in_use)
        self._g_reserved.set(self._reserved)
        self._g_bytes.set(in_use * self.block_nbytes)
        self._g_occupancy.set((in_use + self._reserved) / max(1, cap))
        self._g_frag.set(
            0.0 if in_use == 0
            else 1.0 - self._used_rows / (in_use * self.block_size)
        )

    # ------------------------------------------------------------------ #
    # allocation
    # ------------------------------------------------------------------ #

    @property
    def capacity(self) -> int:
        """Allocatable blocks (excludes the trash block)."""
        return self.num_blocks - 1

    @property
    def in_use(self) -> int:
        return self.capacity - len(self._free)

    @property
    def reserved(self) -> int:
        return self._reserved

    @property
    def available(self) -> int:
        """Blocks a NEW reservation may claim: free minus already
        committed to other requests' lazy growth."""
        return len(self._free) - self._reserved

    def reserve(self, n: int, *, count_failure: bool = True) -> None:
        """Commit ``n`` blocks to a request (taken lazily via
        :meth:`take`); raises :class:`PoolExhausted` — and counts an
        alloc failure — when fewer than ``n`` unreserved free blocks
        exist. All-or-nothing, so a reserved request's table growth can
        never fail mid-decode.

        ``count_failure=False`` suppresses the failure counter: the
        engine RETRIES a parked admission every dispatcher pass, and
        the counter must tally pool-pressure INCIDENTS (one per park,
        pairing with the flight recorder's ``pool_pressure`` events),
        not retry spin."""
        if n < 0:
            raise ValueError(f"cannot reserve {n} blocks")
        if n > self.available:
            if count_failure:
                self._m_alloc_failures.inc()
            self._sync_gauges()
            raise PoolExhausted(
                f"kv pool exhausted: {n} blocks needed, "
                f"{self.available} available "
                f"({self.in_use} in use, {self._reserved} reserved, "
                f"capacity {self.capacity})",
                needed=n, available=self.available,
            )
        self._reserved += n
        self._sync_gauges()

    def take(self) -> int:
        """Convert one reserved block into a concrete id (table
        growth). The caller must hold an unconverted reservation — the
        free list cannot be empty then (reservation invariant)."""
        if self._reserved < 1:
            raise RuntimeError("take() without a reservation")
        bid = self._free.pop()
        self._reserved -= 1
        self._m_allocated.inc()
        self._sync_gauges()
        return bid

    def give(self, ids: Sequence[int], unreserve: int = 0) -> None:
        """Return taken blocks to the free list and drop ``unreserve``
        never-taken reservation slots (a finished/failed request frees
        both in one call)."""
        for bid in ids:
            if not 1 <= bid < self.num_blocks:
                raise ValueError(f"block id {bid} outside pool")
            self._free.append(bid)
        if unreserve < 0 or unreserve > self._reserved:
            raise ValueError(
                f"unreserve {unreserve} outside [0, {self._reserved}]"
            )
        self._reserved -= unreserve
        if ids:
            self._m_freed.inc(len(ids))
        if self.in_use < 0:  # pragma: no cover - double-free guard
            raise RuntimeError("kv pool double-free")
        self._sync_gauges()

    def note_preempted(self, n: int) -> None:
        """Count ``n`` blocks released by a scheduler preemption (the
        engine calls this at eviction time; the actual free rides the
        normal deferred-fence :meth:`give` path, so the flow counters
        stay consistent — this series only attributes the CAUSE)."""
        if n > 0:
            self._m_preempted.inc(n)

    def note_used_rows(self, rows: int) -> None:
        """Update the fragmentation gauge's numerator: total rows
        actually holding KV across every in-use block (the engine's
        host-side fill estimate)."""
        self._used_rows = max(0, int(rows))
        self._sync_gauges()

    def reset(self) -> None:
        """Return EVERY block to the free list (engine recovery: the
        device pool arrays were invalidated wholesale, so host
        bookkeeping resets with them)."""
        freed = self.in_use
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._reserved = 0
        self._used_rows = 0
        self.generation += 1
        if freed:
            self._m_freed.inc(freed)
        self._sync_gauges()

    # ------------------------------------------------------------------ #
    # views
    # ------------------------------------------------------------------ #

    def blocks_for_rows(self, rows: int) -> int:
        """Blocks needed to cover ``rows`` KV rows."""
        return -(-max(0, int(rows)) // self.block_size)

    def stats(self) -> dict:
        """The ``kv_pool`` section of ``DecodeEngine.stats()`` — a thin
        view over this instance's registry series."""
        in_use = self.in_use
        return {
            "block_size": self.block_size,
            "capacity_blocks": self.capacity,
            "blocks_in_use": in_use,
            "blocks_reserved": self._reserved,
            "blocks_free": len(self._free),
            "bytes_in_use": in_use * self.block_nbytes,
            "occupancy": round(
                (in_use + self._reserved) / max(1, self.capacity), 3
            ),
            "fragmentation": round(
                0.0 if in_use == 0
                else 1.0 - self._used_rows / (in_use * self.block_size), 3
            ),
            "allocated_blocks": int(self._m_allocated.value),
            "freed_blocks": int(self._m_freed.value),
            "alloc_failures": int(self._m_alloc_failures.value),
            "preempted_blocks": int(self._m_preempted.value),
        }

    def reset_stats(self) -> None:
        """Zero the flow counters (benchmarks call this between
        phases); the occupancy gauges re-sync to live contents."""
        for m in (
            self._m_allocated, self._m_freed, self._m_alloc_failures,
            self._m_preempted,
        ):
            m.reset()
        self._sync_gauges()
