"""Serving-mode auto-selection: encode the measured engine-vs-batcher
crossover instead of making the operator read BASELINE.md.

Round-3 measurements (BASELINE.md): the full-batch micro-batcher wins
closed-loop p50 when the host↔device round trip dominates a decode
chunk (the engine pays per-chunk dispatch/harvest interactions that the
monolithic generate amortizes); the continuous-batching engine wins the
tail — and open-loop traffic — once a decode chunk costs at least a
round trip, because late arrivals join at chunk boundaries instead of
waiting out a whole in-flight generation. The crossover is therefore
``decode_chunk_ms >= rtt_ms``: when the device does a round-trip's
worth of work per chunk, chunk pipelining is free and the join
granularity pays for itself.

:func:`choose_serving_mode` measures both sides at warmup (a few
dispatch round trips + two short generates) and returns the decision
with its evidence — surfaced in ``/stats`` by the serving benches so an
operator can audit the choice.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

__all__ = [
    "choose_serving_mode",
    "decide_mode",
    "measure_decode_chunk_ms",
    "measure_rtt_ms",
]


def decide_mode(*, rtt_ms: float, decode_chunk_ms: float) -> str:
    """The pure decision rule (unit-tested both ways): ``"engine"`` when
    one decode chunk costs at least one host↔device round trip, else
    ``"batcher"``."""
    if rtt_ms < 0 or decode_chunk_ms < 0:
        raise ValueError(
            f"timings must be non-negative (rtt={rtt_ms}, "
            f"chunk={decode_chunk_ms})"
        )
    return "engine" if decode_chunk_ms >= rtt_ms else "batcher"


def measure_rtt_ms(reps: int = 10) -> float:
    """Median host→device→host round trip of a tiny transfer — the
    per-interaction cost the engine pays per chunk (measured ~119 ms
    through the tunneled backend here, ~O(0.1 ms) on a local device)."""
    import jax
    import numpy as np

    times = []
    for i in range(max(3, reps)):
        t0 = time.perf_counter()
        arr = jax.device_put(np.int32(i))
        np.asarray(arr)  # blocks on the readback
        times.append(time.perf_counter() - t0)
    return float(np.median(times) * 1e3)


def measure_decode_chunk_ms(
    module: Any,
    params: Any,
    *,
    chunk_steps: int = 8,
    prompt_len: int = 16,
    reps: int = 3,
) -> float:
    """One decode chunk's device time: generate ``chunk_steps + 1``
    tokens and ``1`` token from the same short prompt; the difference
    isolates ``chunk_steps`` decode steps from prefill + dispatch.
    Costs two small compiles — run at warmup, not per request."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.models.generate import make_generator

    max_len = prompt_len + chunk_steps + 1
    prompt = jnp.ones((1, prompt_len), jnp.int32)

    def best_of(gen):
        gen(params, prompt)  # compile
        best = float("inf")
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            np.asarray(gen(params, prompt))
            best = min(best, time.perf_counter() - t0)
        return best * 1e3

    long_ms = best_of(
        make_generator(module, max_new_tokens=chunk_steps + 1, max_len=max_len)
    )
    short_ms = best_of(
        make_generator(module, max_new_tokens=1, max_len=max_len)
    )
    return max(0.0, long_ms - short_ms)


def choose_serving_mode(
    module: Any = None,
    params: Any = None,
    *,
    chunk_steps: int = 8,
    rtt_ms: Optional[float] = None,
    decode_chunk_ms: Optional[float] = None,
) -> Dict[str, Any]:
    """Measure (or accept) both timings and pick the serving mode.

    Returns ``{"mode", "rtt_ms", "decode_chunk_ms", "rule"}`` — pass the
    dict into the serving stats so ``/stats`` records why this mode is
    running. Provide ``module``+``params`` to measure, or inject both
    timings directly (tests, pre-measured deployments).
    """
    if rtt_ms is None:
        rtt_ms = measure_rtt_ms()
    if decode_chunk_ms is None:
        if module is None or params is None:
            raise ValueError(
                "either pass decode_chunk_ms or module+params to measure it"
            )
        decode_chunk_ms = measure_decode_chunk_ms(
            module, params, chunk_steps=chunk_steps
        )
    return {
        "mode": decide_mode(rtt_ms=rtt_ms, decode_chunk_ms=decode_chunk_ms),
        "rtt_ms": round(rtt_ms, 2),
        "decode_chunk_ms": round(decode_chunk_ms, 2),
        "rule": "engine iff decode_chunk_ms >= rtt_ms (BASELINE.md round 3)",
    }
