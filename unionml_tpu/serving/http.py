"""Dependency-free HTTP serving transport (stdlib only).

Same endpoint surface as the reference's FastAPI app
(reference: unionml/fastapi.py:15-70):

- ``GET /`` — HTML landing page,
- ``POST /predict`` — body ``{"inputs": {reader kwargs}}`` or
  ``{"features": ...}``; features flow through
  ``dataset.get_features`` then the (optionally micro-batched) predictor,
- ``POST /predict/stream`` — Server-Sent Events: one ``data:`` event per
  harvested token chunk (``{"tokens": [...]}``), terminated by
  ``{"done": true, "n_tokens": N}``. Requires a streaming predictor
  (``ServingApp(stream=...)`` — e.g. ``DecodeEngine.generate_stream``);
  concatenated chunks are identical to the ``/predict`` response. Time
  to first token ≈ queue + prefill, not the full generation — the
  latency win streaming exists for.
- ``GET /health`` — readiness:
  ``{"status": ok|degraded|draining, "model_loaded": bool,
  "queue_depth": int, "breaker_open": bool}`` sourced from the active
  engine/batcher (``health=`` hook); any status other than ``ok``
  answers **503** on both transports so load balancers stop routing
  here (docs/robustness.md),
- ``GET /stats`` — serving observability: per-request queue-wait /
  prefill / decode (or device) time splits — plus a ``ttft_ms``
  percentile from the engine, and a ``prefix_cache`` section
  (hit rate, prefill-tokens-saved, store bytes) when the engine runs
  an automatic prefix KV-cache — from the active batcher or decode
  engine (no reference counterpart — needed to attribute tail latency
  between transport queueing and device time),
- ``GET /metrics`` — Prometheus text exposition of the shared
  :mod:`unionml_tpu.telemetry` registry (engine, batcher, prefix-cache,
  HTTP-layer, trainer, and per-program cost-analysis/MFU series in one
  scrape surface, plus the standard ``process_start_time_seconds`` /
  ``unionml_tpu_build_info`` gauges),
- ``POST /debug/profile?seconds=N`` — on-demand ``jax.profiler``
  capture; returns the trace artifact directory (409 while another
  capture runs),
- ``GET /debug/memory`` — per-device memory stats + live-buffer census,
- ``GET /debug/flight?n=K&tenant=`` — the request flight recorder's
  newest events (admissions, decode chunks, sheds, recoveries) for
  after-the-fact explanation of a 429/504/recovery
  (docs/observability.md); events carry the submitting tenant, so an
  overload postmortem can filter to who was shed,
- ``GET /debug/usage`` — per-tenant resource vectors from the usage
  ledger (``ServingApp(usage=...)``): queue/prefill/decode splits,
  attributed device-seconds and FLOPs, prefix-cache savings, and the
  decode capacity-headroom estimate (docs/observability.md "Usage
  metering & cost attribution"),
- ``GET /debug/cache/peek?prompt=1,2,3`` — the prefix cache's
  read-only peek over HTTP (``ServingApp(cache_peek=...)``): how many
  leading tokens of the comma-separated prompt this process holds
  cached KV for. The fleet router's ``HttpReplica`` probes it
  (TTL-cached) for cache-affinity routing ACROSS hosts — the remote
  twin of the in-process ``RadixPrefixCache.peek``, and like it the
  probe takes no lease, bumps no LRU, and moves no hit/miss counters,
- ``GET /debug/trace?format=chrome|jsonl`` — the trace recorder's
  Chrome-trace / JSON-lines export over HTTP (no shelling into the
  process to pull a trace),
- ``GET /debug/slo`` — the SLO watchdog's burn-rate report when the
  app was built with one (``ServingApp(slo=...)``).

Every response carries an ``X-Request-ID`` header (a generated
telemetry request id) and lands in the per-endpoint
``unionml_http_requests_total`` / ``unionml_http_request_ms`` series.

Tenant identity (docs/observability.md "Usage metering & cost
attribution"): every request may carry an ``X-Tenant-ID`` header
(default ``anonymous``; values over 64 chars or with non-printable
characters answer **422** — a hostile header must never mint a label
value). The validated tenant is echoed on every response alongside
``X-Request-ID``, and predict routes open a
:func:`~unionml_tpu.serving.usage.tenant_scope` so engine/batcher
submissions bill their resource vectors to it.

Scheduling priority (docs/robustness.md "Preemption & fairness"):
every request may carry an ``X-Priority`` header (``high`` /
``normal`` / ``low``, default ``normal``; anything else answers
**422** — the value set is closed). The validated class is echoed on
every response and predict routes open a
:func:`~unionml_tpu.serving.scheduler.priority_scope`, so engine
submissions enter the preemptive scheduler's waiting room under the
caller's class.

Model-version pinning (docs/robustness.md "Rollouts & rollback"):
every request may carry an ``X-Model-Version`` header (a registry
version slug, default ``auto`` = route wherever the rollout split
says; malformed slugs answer **422** — the grammar is closed). The
validated value is echoed on every response and predict routes open a
:func:`~unionml_tpu.serving.scheduler.model_version_scope`, so a
version-aware router pins the request to replicas serving exactly
those weights.

Distributed tracing (docs/observability.md): every request parses an
inbound W3C ``traceparent`` header (a fresh root is minted when absent
or malformed — tracing metadata can never 5xx a request) and the
response echoes a ``traceparent`` carrying the same trace id, so
callers can stitch the full request tree. ``POST /predict`` and
``/predict/stream`` additionally open a recorded server timeline and a
:func:`~unionml_tpu.telemetry.trace_scope` around the predictor call,
so engine/batcher spans join the caller's trace with connected parent
links. ``ServingApp(otlp_endpoint=...)`` (or
``UNIONML_TPU_OTLP_ENDPOINT``) starts a background
:class:`~unionml_tpu.exporters.OtlpExporter` pushing spans and metric
snapshots to an OTLP/HTTP collector.

Fault tolerance at the transport boundary (docs/robustness.md): an
``X-Deadline-Ms`` request header opens a :func:`~unionml_tpu.serving
.faults.deadline_scope` around the predictor call, so engine/batcher
submissions shed the request once the budget expires; typed serving
errors map to statuses — :class:`~unionml_tpu.serving.faults
.Overloaded` → **429** with ``Retry-After``,
:class:`~unionml_tpu.serving.faults.EngineUnavailable` (breaker open /
draining) → **503** with ``Retry-After``, :class:`~unionml_tpu.serving
.faults.DeadlineExceeded` → **504**. ``ServingApp.drain()`` stops
admissions app-wide and flips ``/health`` to ``draining``/503.

Startup model loading mirrors fastapi.py:22-34: ``UNIONML_MODEL_PATH``
env first, then the remote registry when ``remote=True``.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from contextlib import contextmanager
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Iterator, Optional
from urllib.parse import parse_qs, urlsplit

import numpy as np

from unionml_tpu import telemetry
from unionml_tpu._logging import logger
from unionml_tpu.serving.faults import (
    DeadlineExceeded,
    EngineUnavailable,
    Overloaded,
    deadline_scope,
    http_fault_response,
    parse_deadline_header,
)
from unionml_tpu.serving.scheduler import (
    DEFAULT_MODEL_VERSION,
    DEFAULT_PRIORITY,
    model_version_scope,
    priority_scope,
    token_cap_scope,
    validate_model_version,
    validate_priority,
    validate_token_cap,
)
from unionml_tpu.serving.usage import (
    DEFAULT_TENANT,
    tenant_scope,
    validate_tenant,
)

# bound HTTP label cardinality: unknown paths share one series instead
# of letting a scanner mint a metric per probed URL
KNOWN_ROUTES = (
    "/", "/predict", "/predict/stream", "/health", "/stats", "/metrics",
    "/debug/profile", "/debug/memory", "/debug/flight", "/debug/trace",
    "/debug/slo", "/debug/usage", "/debug/cache/peek", "/debug/fleet",
    "/debug/rollout", "/debug/kv/export", "/debug/kv/import",
    "/debug/goodput", "/debug/tail",
)

# the routes that open a RECORDED trace timeline (a server span the
# engine/batcher spans parent to); every other route still parses and
# echoes traceparent, but health probes and scrapes must not churn the
# trace ring or the OTLP export queue
TRACED_ROUTES = ("/predict", "/predict/stream")

LANDING_HTML = """<html><head><title>unionml-tpu</title></head>
<body><h1>unionml-tpu serving: {name}</h1>
<p>POST /predict with {{"inputs": ...}} or {{"features": ...}}</p>
<p>GET /health</p></body></html>"""


def _to_jsonable(obj: Any) -> Any:
    if isinstance(obj, (bool, int, float, str, type(None))):
        return obj
    if hasattr(obj, "tolist"):  # numpy / jax arrays and scalars
        return np.asarray(obj).tolist()
    if hasattr(obj, "to_dict"):  # DataFrame
        return obj.to_dict(orient="records")
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(o) for o in obj]
    if isinstance(obj, dict):
        return {str(k): _to_jsonable(v) for k, v in obj.items()}
    try:
        return np.asarray(obj).tolist()
    except Exception:
        return str(obj)


class ServingApp:
    """Holds the model + batcher; dispatches routes for any transport."""

    def __init__(
        self,
        model,
        *,
        remote: bool = False,
        app_version: Optional[str] = None,
        model_version: str = "latest",
        batch: bool = False,
        model_path_env: str = "UNIONML_MODEL_PATH",
        warmup: Optional[Any] = None,
        stats: Optional[Any] = None,
        stream: Optional[Any] = None,
        extra_stats: Optional[dict] = None,
        registry: Optional[telemetry.MetricsRegistry] = None,
        health: Optional[Any] = None,
        drain: Optional[Any] = None,
        flight: Optional[telemetry.FlightRecorder] = None,
        tracer: Optional[telemetry.TraceRecorder] = None,
        otlp_endpoint: Optional[str] = None,
        slo: Optional[Any] = None,
        usage: Optional[Any] = None,
        cache_peek: Optional[Any] = None,
        kv_export: Optional[Any] = None,
        kv_import: Optional[Any] = None,
        goodput: Optional[Any] = None,
        **batcher_kwargs,
    ):
        """``warmup``: optional callable invoked with the loaded model
        object after ``setup_model`` — pre-compile every serving
        executable there (e.g. ``make_lm_predictor``'s ``.warmup``), or
        the first live request per shape stalls behind a multi-second
        XLA compile.

        ``stats``: optional zero-arg callable whose dict is served at
        ``GET /stats`` (e.g. ``DecodeEngine.stats`` when the predictor
        wraps a continuous-batching engine); defaults to the
        micro-batcher's stats when ``batch=True``.

        ``stream``: optional ``(model_object, features) -> iterator of
        token chunks`` enabling ``POST /predict/stream`` (SSE). Wrap
        ``DecodeEngine.generate_stream`` — the batcher path computes all
        tokens in one device call, so it has nothing incremental to
        stream.

        ``extra_stats``: optional static dict merged into every
        ``GET /stats`` response (deployment metadata — e.g. the
        serving-mode auto-selection decision from
        :func:`unionml_tpu.serving.auto.choose_serving_mode`).

        ``registry``: explicit :class:`~unionml_tpu.telemetry
        .MetricsRegistry` served at ``GET /metrics``; defaults to the
        process-global registry, so an engine or trainer built anywhere
        in the process shows up in this app's scrape.

        ``health``: optional zero-arg callable returning the readiness
        dict merged into ``GET /health`` (``DecodeEngine.health`` when
        the predictor wraps an engine); defaults to the micro-batcher's
        when ``batch=True``. A non-``ok`` status answers 503.

        ``drain``: optional callable (accepting one optional timeout
        argument) invoked by :meth:`drain` — wire
        ``DecodeEngine.drain`` so the app-level drain also finishes the
        engine's in-flight streams; defaults to the micro-batcher's.

        ``flight``: explicit :class:`~unionml_tpu.telemetry
        .FlightRecorder` served at ``GET /debug/flight``; defaults to
        the process-global recorder, where engines and batchers record
        by default — so the postmortem surface covers them without
        extra wiring.

        ``tracer``: explicit :class:`~unionml_tpu.telemetry
        .TraceRecorder` for the transport's server spans and
        ``GET /debug/trace``; defaults to the process-global recorder
        (where engines record), so the exported trace holds the
        transport AND engine spans of each request in one tree.

        ``otlp_endpoint``: an OTLP/HTTP collector base URL (e.g.
        ``http://collector:4318``) — when set (or via the
        ``UNIONML_TPU_OTLP_ENDPOINT`` env var), the app runs a
        background :class:`~unionml_tpu.exporters.OtlpExporter`
        pushing finished request spans and periodic metric snapshots;
        :meth:`shutdown` closes it.

        ``slo``: a :class:`~unionml_tpu.slo.SloWatchdog` — evaluated on
        every ``GET /health`` (the probe cadence is the sampling
        cadence) and served at ``GET /debug/slo``; a breached
        objective flips health to ``degraded`` → 503, so load
        balancers react to objective burn, not just crash loops.

        ``usage``: a :class:`~unionml_tpu.serving.usage.UsageLedger` —
        the SAME ledger the engine/batcher records into (e.g.
        ``engine.usage``) — served at ``GET /debug/usage``: per-tenant
        resource vectors, cache savings, and the capacity-headroom
        estimate (docs/observability.md "Usage metering & cost
        attribution").

        ``cache_peek``: a ``(prompt token ids) -> int`` read-only
        probe — wire the engine's ``prefix_cache.peek`` (or a
        router's fleet-wide ``cached_prefix_len``) — served at
        ``GET /debug/cache/peek?prompt=...`` so the fleet router's
        :class:`~unionml_tpu.serving.router.HttpReplica` can make
        cache-affinity routing decisions across hosts.

        ``kv_export`` / ``kv_import``: the cross-host KV handoff
        surface (docs/serving.md "Disaggregated serving") — wire
        ``engine.kv_export`` and ``engine.kv_import``. ``POST
        /debug/kv/export`` (body ``{"prompt": [...]}``) answers this
        process's cached block entries covering the prompt, wire-
        encoded; ``POST /debug/kv/import`` (body ``{"entries":
        [...]}``) attaches a donor's entries to this process's store.
        A disaggregated router uses the pair to move a prefill
        replica's finalized KV onto a decode replica on another host;
        both answer 422 when unwired.

        ``goodput``: a zero-arg callable returning the serving goodput
        plane's report — wire ``engine.goodput_report`` — served at
        ``GET /debug/goodput``: batch-occupancy classification
        (full-batch / padded-slot / prefill-mix / idle device passes),
        goodput + occupancy + KV-pressure ratios, achieved tokens/s
        tied to the introspection MFU gauges, and the perf-regression
        watchdog advisory (docs/observability.md "Serving goodput &
        tail attribution"). Answers 422 when unwired."""
        self.model = model
        self.remote = remote
        self.app_version = app_version
        self.model_version = model_version
        self.model_path_env = model_path_env
        self.batch = batch
        self.warmup = warmup
        self._stats_fn = stats
        self._stream_fn = stream
        self._health_fn = health
        self._drain_fn = drain
        self._draining = False
        self._extra_stats = dict(extra_stats or {})
        self._batcher = None
        self._batcher_kwargs = batcher_kwargs
        self._server: Optional[ThreadingHTTPServer] = None
        self.registry = registry if registry is not None else telemetry.get_registry()
        self._flight = (
            flight if flight is not None else telemetry.get_flight_recorder()
        )
        self._tracer = tracer if tracer is not None else telemetry.get_tracer()
        self._slo = slo
        self._usage = usage
        self._cache_peek = cache_peek
        self._kv_export = kv_export
        self._kv_import = kv_import
        self._goodput = goodput
        self._otlp = None
        endpoint = otlp_endpoint or os.getenv("UNIONML_TPU_OTLP_ENDPOINT")
        if endpoint:
            from unionml_tpu.exporters import OtlpExporter

            self._otlp = OtlpExporter(
                endpoint, registry=self.registry, tracer=self._tracer
            )
        self._m_http_requests = self.registry.counter(
            "unionml_http_requests_total",
            "HTTP requests served, by transport/path/status.",
            ("transport", "path", "status"),
        )
        self._m_http_errors = self.registry.counter(
            "unionml_http_errors_total",
            "HTTP responses with status >= 400, by transport/path.",
            ("transport", "path"),
        )
        self._h_http_ms = self.registry.histogram(
            "unionml_http_request_ms",
            "Request wall time at the transport boundary.",
            ("transport", "path"),
        )

    # -- lifecycle --------------------------------------------------------

    def setup_model(self):
        """Load the artifact (reference: fastapi.py:22-34)."""
        model_path = os.getenv(self.model_path_env)
        if model_path is not None and model_path != "":
            self.model.load(model_path)
        elif self.remote:
            from unionml_tpu.remote import load_latest_artifact

            load_latest_artifact(
                self.model, app_version=self.app_version, model_version=self.model_version
            )
        if self.model.artifact is None:
            raise RuntimeError(
                f"Model artifact unavailable: set {self.model_path_env} or serve "
                "with remote=True against a deployed app."
            )
        if self.batch:
            from unionml_tpu.serving.batcher import MicroBatcher

            predictor = self.model._predictor
            model_object = self.model.artifact.model_object
            if self.model._predict_step_options.get("jit"):
                from unionml_tpu.execution import jit_predictor

                predictor = jit_predictor(predictor)
            self._batcher = MicroBatcher(
                lambda feats: predictor(model_object, feats),
                # the app's scrape, /debug/flight, /debug/trace, and
                # /debug/usage must cover its own batcher even when the
                # app was built with isolated sinks — `usage` in
                # particular has no other route into an app-built
                # batcher (ServingApp(usage=) consumes the kwarg name)
                **{
                    "registry": self.registry,
                    "flight": self._flight,
                    "tracer": self._tracer,
                    "usage": self._usage,
                    **self._batcher_kwargs,
                },
            )
        if self.warmup is not None:
            n = self.warmup(self.model.artifact.model_object)
            logger.info(f"serving warmup done ({n if n is not None else '?'} executables)")

    # -- route handlers ---------------------------------------------------

    def root(self) -> str:
        return LANDING_HTML.format(name=self.model.name)

    def health(self) -> dict:
        """Readiness: ``status`` is ``ok`` / ``degraded`` (engine
        circuit breaker open) / ``draining``, plus the queue depth and
        breaker state from the active engine/batcher. Transports answer
        503 for any non-``ok`` status (see :meth:`health_status`)."""
        out = {
            "status": "ok",
            "model_loaded": self.model.artifact is not None,
            "queue_depth": 0,
            "breaker_open": False,
        }
        src = self._health_fn
        if src is None and self._batcher is not None:
            src = self._batcher.health
        if src is not None:
            out.update(src())
        if self._slo is not None:
            # the watchdog samples on the health-probe cadence; a
            # breached objective degrades an otherwise-ok replica so
            # the balancer reacts to objective burn, not just crashes
            breached = self._slo.evaluate().get("breached", [])
            out["slo_breached"] = breached
            if breached and out["status"] == "ok":
                out["status"] = "degraded"
        if self._draining:
            # app-level drain overrides the component view: this
            # process is going away even if the engine itself is idle
            out["status"] = "draining"
        return out

    def health_status(self, health: dict) -> int:
        """HTTP status for a :meth:`health` body: 503 whenever the app
        is not ready to take traffic (degraded/draining), so load
        balancers and k8s readiness probes stop routing here."""
        return 200 if health.get("status") == "ok" else 503

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful drain: stop admitting (predict/stream answer 503,
        ``/health`` flips to ``draining``) and delegate to the wired
        component drain (``drain=`` hook, or the micro-batcher's) so
        in-flight requests and streams finish. Returns True when fully
        drained. The HTTP server keeps answering health/metrics —
        shutdown is still :meth:`shutdown`."""
        self._draining = True
        fn = self._drain_fn
        if fn is None and self._batcher is not None:
            fn = self._batcher.drain
        if fn is None:
            return True
        return bool(fn(timeout))

    def resume(self) -> None:
        """Reopen admissions after :meth:`drain` (the component's own
        ``resume`` must be called separately if it was drained)."""
        self._draining = False

    def stats(self) -> dict:
        if self._stats_fn is not None:
            base = dict(self._stats_fn())
        elif self._batcher is not None:
            base = self._batcher.stats()
        else:
            base = {"engine": "direct"}  # per-request predictors: no queue
        return {**base, **self._extra_stats} if self._extra_stats else base

    def reset_stats(self) -> None:
        """Zero the batcher's observability window (no-op for direct or
        custom-stats serving — reset the custom source directly)."""
        if self._batcher is not None:
            self._batcher.reset_stats()

    def metrics_text(self) -> str:
        """The ``GET /metrics`` body: Prometheus text exposition of the
        app's registry (shared by both transports so they cannot drift).
        Serve with ``telemetry.EXPOSITION_CONTENT_TYPE``."""
        # refresh the standard process gauges (process_start_time_
        # seconds, unionml_tpu_build_info) so every scraped registry —
        # isolated ones included — carries them
        telemetry.publish_process_metrics(self.registry)
        return self.registry.exposition()

    # -- debug/introspection surface (shared by both transports) ----------

    def debug_profile(self, seconds: float = 2.0) -> dict:
        """``POST /debug/profile?seconds=N``: capture an on-demand
        ``jax.profiler`` trace and return its artifact directory
        (docs/observability.md). Raises
        :class:`~unionml_tpu.introspection.ProfileInProgress` (→ 409)
        when a capture is already running, ``ValueError`` (→ 422) for a
        non-positive duration."""
        from unionml_tpu.introspection import capture_profile

        return capture_profile(seconds)

    def debug_memory(self) -> dict:
        """``GET /debug/memory``: per-device ``memory_stats()`` plus a
        live-buffer census (count/bytes by dtype and top shapes)."""
        from unionml_tpu.introspection import device_memory_breakdown

        return device_memory_breakdown()

    def debug_flight(
        self, n: Optional[int] = None, kind: Optional[str] = None,
        rid: Optional[str] = None, tenant: Optional[str] = None,
        phase: Optional[str] = None,
    ) -> dict:
        """``GET /debug/flight?n=K``: the newest ``K`` request
        lifecycle events from the flight recorder (all retained when
        unset), optionally filtered by event kind / request id /
        tenant tag (``?tenant=`` names who was shed in an overload
        postmortem) / serving-phase tag (``?phase=prefill`` isolates
        one pool of a disaggregated fleet — handoff events carry both
        legs' phases and match either). ``wall_offset_ms`` is the
        value to ADD to each event's monotonic ``t_ms`` for epoch
        milliseconds — the fleet router's flight merge rebases
        per-host rings with it, since raw monotonic readings are
        incomparable across machines."""
        return {
            **self._flight.stats(),
            "wall_offset_ms": round(telemetry.wall_clock_offset_ms(), 3),
            "events": self._flight.dump(
                n=n, kind=kind, rid=rid, tenant=tenant, phase=phase,
            ),
        }

    def debug_usage(self) -> dict:
        """``GET /debug/usage``: the usage ledger's per-tenant resource
        vectors, attribution-identity totals, cache savings, and
        capacity-headroom estimate. Raises ``ValueError`` (→ 422) when
        the app has no ledger."""
        if self._usage is None:
            raise ValueError(
                "no usage ledger on this app — construct "
                "ServingApp(usage=engine.usage) with a metering engine"
            )
        return self._usage.report()

    def debug_cache_peek(self, prompt: Any) -> dict:
        """``GET /debug/cache/peek?prompt=1,2,3``: how many leading
        tokens of ``prompt`` (comma-separated ids, or a list) this
        process holds cached KV for — the remote half of cache-affinity
        routing. Raises ``ValueError`` (→ 422) when the app has no
        peek source or the prompt doesn't parse."""
        if self._cache_peek is None:
            raise ValueError(
                "no cache peek on this app — construct "
                "ServingApp(cache_peek=engine.prefix_cache.peek) with a "
                "prefix-cached engine"
            )
        if isinstance(prompt, str):
            parts = [p for p in prompt.split(",") if p.strip() != ""]
            if not parts:
                raise ValueError(
                    "prompt must be non-empty comma-separated token ids"
                )
            tokens = [int(p) for p in parts]
        else:
            tokens = [int(t) for t in prompt]
            if not tokens:
                raise ValueError("prompt must be non-empty")
        return {"cached_prefix_len": int(self._cache_peek(tokens))}

    def debug_kv_export(self, prompt: Any) -> dict:
        """``POST /debug/kv/export`` (body ``{"prompt": [...]}``): the
        cached KV block entries covering ``prompt``, wire-encoded —
        the donor half of the cross-host disaggregated handoff
        (docs/serving.md "Disaggregated serving"). Raises
        ``ValueError`` (→ 422) when the app has no export source or
        the prompt doesn't parse."""
        from unionml_tpu.serving.prefix_cache import encode_entries

        if self._kv_export is None:
            raise ValueError(
                "no KV export on this app — construct "
                "ServingApp(kv_export=engine.kv_export) with a "
                "prefix-cached engine"
            )
        tokens = [int(t) for t in prompt]
        if not tokens:
            raise ValueError("prompt must be non-empty token ids")
        entries = self._kv_export(tokens)
        return {"entries": encode_entries(entries), "blocks": len(entries)}

    def debug_kv_import(self, entries: Any) -> dict:
        """``POST /debug/kv/import`` (body ``{"entries": [...]}``):
        attach wire-encoded donor entries to this process's host block
        store — the import half of the cross-host handoff AND of
        remote fleet warming. Raises ``ValueError`` (→ 422) when the
        app has no import sink or the body is malformed."""
        from unionml_tpu.serving.prefix_cache import decode_entries

        if self._kv_import is None:
            raise ValueError(
                "no KV import on this app — construct "
                "ServingApp(kv_import=engine.kv_import) with a "
                "prefix-cached engine"
            )
        if not isinstance(entries, (list, tuple)):
            raise ValueError("'entries' must be a list of KV entries")
        attached = int(self._kv_import(decode_entries(entries)))
        return {"attached": attached}

    def debug_trace(
        self,
        format: str = "chrome",
        rid: Optional[str] = None,
        trace: Optional[str] = None,
    ):
        """``GET /debug/trace?format=chrome|jsonl`` — the trace
        recorder's retained requests — OR, with ``?rid=`` /
        ``?trace=``, ONE stitched end-to-end timeline:
        ``(body, content_type)``.

        - ``format=chrome`` (default) is the Perfetto-loadable
          trace-event JSON; ``jsonl`` one span per line for log
          shippers. Raises ``ValueError`` (→ 422) for any other
          format.
        - ``rid=<X-Request-ID>`` resolves the id a client holds into
          its trace and answers the stitched timeline document
          (:func:`~unionml_tpu.telemetry.stitched_trace`): every
          retained local timeline of that trace — transport server
          span, engine/batcher spans, and on a router app the routing
          spans plus fetched replica spans — as one span list with
          connected W3C parent links. Unknown rids raise
          ``ValueError`` (→ 422).
        - ``trace=<trace-id>`` stitches directly by trace id and
          answers an EMPTY document when this process holds nothing
          for it (a fleet peer probing every replica must get a
          degrading answer, not an error).
        """
        if rid is not None or trace is not None:
            trace_id = trace
            if trace_id is None:
                trace_id = self._tracer.find_trace_id(rid)
                if trace_id is None:
                    raise ValueError(
                        f"unknown request id {rid!r} (not in the trace "
                        "recorder's retained window)"
                    )
            doc = telemetry.stitched_trace(
                trace_id, self._tracer.requests_for_trace(trace_id),
            )
            return doc, "application/json"
        if format == "chrome":
            return self._tracer.export_chrome(), "application/json"
        if format == "jsonl":
            return self._tracer.export_jsonl(), "application/x-ndjson"
        raise ValueError(
            f"unknown trace format {format!r} (use chrome or jsonl)"
        )

    def debug_fleet(self) -> dict:
        """``GET /debug/fleet``: the fleet operator dashboard — only a
        router app (:func:`~unionml_tpu.serving.router
        .make_router_app`) has a fleet to report. Raises ``ValueError``
        (→ 422) here."""
        raise ValueError(
            "no fleet on this app — serve a FleetRouter via "
            "make_router_app for the fleet dashboard"
        )

    def debug_rollout(self) -> dict:
        """``GET /debug/rollout``: the rollout operator dashboard —
        only a router app whose :class:`~unionml_tpu.serving.rollout
        .RolloutController` is attached has one to report. Raises
        ``ValueError`` (→ 422) here."""
        raise ValueError(
            "no rollout controller on this app — serve a FleetRouter "
            "via make_router_app and attach a RolloutController"
        )

    def debug_slo(self) -> dict:
        """``GET /debug/slo``: a fresh SLO watchdog evaluation (burn
        rates per objective and window, breach flags), plus a
        ``serving`` block of TTFT/ITL percentile rows and per-engine
        goodput ratios read from the serving perf plane's histograms —
        the rows an ITL- or goodput-targeted ``SloObjective`` (and the
        per-pool autoscalers) key on. Raises ``ValueError`` (→ 422)
        when the app has no watchdog."""
        if self._slo is None:
            raise ValueError(
                "no SLO watchdog on this app — construct "
                "ServingApp(slo=SloWatchdog([...]))"
            )
        report = self._slo.evaluate()
        serving = self._serving_percentiles()
        if serving:
            report["serving"] = serving
        return report

    def _serving_percentiles(self) -> dict:
        """TTFT/ITL percentile rows (exact, over each histogram's
        retained sample window, merged across label children) and the
        per-engine goodput ratio gauges — ``{}`` when no serving perf
        plane has recorded into this app's registry."""
        out: dict = {}
        for family in self.registry.collect():
            if family.name in ("unionml_engine_ttft_ms",
                               "unionml_engine_itl_ms"):
                samples: list = []
                for _values, child in family.children():
                    samples.extend(child.samples())
                if samples:
                    key = ("ttft_ms" if family.name.endswith("ttft_ms")
                           else "itl_ms")
                    out[key] = telemetry.percentile_summary(samples)
            elif family.name == "unionml_serving_goodput_ratio":
                ratios = {
                    values[0]: round(child.value, 6)
                    for values, child in family.children()
                }
                if ratios:
                    out["goodput_ratio"] = ratios
        return out

    def debug_goodput(self) -> dict:
        """``GET /debug/goodput``: the serving goodput plane's report —
        dispatcher-pass classification (full-batch / padded-slot /
        prefill-mix / idle), goodput + occupancy + KV-pressure ratios,
        achieved tokens/s alongside the introspection layer's MFU
        figures, and the perf-regression watchdog advisory. Raises
        ``ValueError`` (→ 422) when the app has no goodput source (or
        the engine's plane is off)."""
        if self._goodput is None:
            raise ValueError(
                "no goodput source on this app — construct "
                "ServingApp(goodput=engine.goodput_report) with a "
                "perf-enabled engine"
            )
        return self._goodput()

    def debug_tail(self, metric: str = "", n: Optional[int] = None) -> dict:
        """``GET /debug/tail?metric=&n=``: the ``n`` slowest recent
        requests by exemplar value of one histogram (default
        ``unionml_engine_decode_ms``), each with its per-phase latency
        split (queue / admission / prefill / decode / ITL, from the
        flight recorder's ``finish`` event) and a ``trace`` link whose
        rid resolves in ``GET /debug/trace?rid=`` — histogram bucket →
        stitched timeline in one hop. Raises ``ValueError`` (→ 422)
        for an unknown or non-histogram metric."""
        name = metric or "unionml_engine_decode_ms"
        family = next(
            (f for f in self.registry.collect() if f.name == name), None
        )
        if family is None:
            raise ValueError(
                f"unknown metric {name!r} (nothing by that name in "
                "this app's registry)"
            )
        if family.kind != "histogram":
            raise ValueError(
                f"metric {name!r} is a {family.kind} — tail exemplars "
                "exist only on histograms"
            )
        k = 5 if n is None else max(1, min(64, int(n)))
        rows = []
        for values, child in family.children():
            labels = dict(zip(family.labelnames, values))
            for value, rid in child.exemplars(k):
                rows.append({
                    "rid": rid,
                    "value_ms": round(value, 3),
                    "labels": labels,
                })
        rows.sort(key=lambda r: r["value_ms"], reverse=True)
        rows = rows[:k]
        segment_keys = (
            "queue_ms", "admission_ms", "prefill_ms", "decode_ms",
            "ttft_ms", "itl_mean_ms", "itl_tokens", "tokens",
        )
        for row in rows:
            events = self._flight.dump(rid=row["rid"], kind="finish")
            if events:
                ev = events[-1]
                row["segments"] = {
                    key: ev[key] for key in segment_keys if key in ev
                }
            row["trace"] = f"/debug/trace?rid={row['rid']}"
        return {"metric": name, "n": k, "requests": rows}

    def open_traced_request(
        self, path: str, raw_traceparent: Optional[str],
        rid: Optional[str] = None,
    ):
        """``(ctx, finish)`` — the non-context-manager seam for
        transports whose response outlives the handler frame (the
        FastAPI streaming route hands its body to the event loop):
        opens the recorded server timeline parented to the inbound
        ``traceparent`` and returns its context plus an idempotent
        ``finish()`` that records the server span and closes the
        timeline — callable exactly-once-effective from any thread.
        Prefer :meth:`traced_request` where the handler frame spans
        the response. ``rid`` keys the timeline under the transport's
        ``X-Request-ID`` so ``/debug/trace?rid=`` resolves the id the
        client actually received."""
        inbound = telemetry.parse_traceparent(raw_traceparent)
        rid = self._tracer.new_request(
            "http", trace_ctx=inbound, rid=rid, path=path,
        )
        ctx = self._tracer.trace_context(rid)
        t0 = time.perf_counter()
        finished = threading.Event()

        def finish() -> None:
            if finished.is_set():
                return
            finished.set()
            # the server span makes the transport visible in the
            # chrome/jsonl exports (which emit recorded spans only; the
            # OTLP export additionally synthesizes the timeline root)
            self._tracer.record_span(
                rid, f"http {path}", t0, time.perf_counter()
            )
            self._tracer.finish_request(rid)

        return ctx, finish

    @contextmanager
    def traced_request(
        self, path: str, raw_traceparent: Optional[str],
        rid: Optional[str] = None,
    ) -> Iterator[telemetry.TraceContext]:
        """One traced transport request (shared by all three
        transports so the propagation contract cannot drift): opens a
        recorded server timeline parented to the inbound
        ``traceparent`` (minting a root when absent/malformed — never
        an error), exposes its context to engine/batcher submissions
        on this thread via :func:`~unionml_tpu.telemetry.trace_scope`,
        and yields the context whose
        :func:`~unionml_tpu.telemetry.format_traceparent` the response
        must echo."""
        ctx, finish = self.open_traced_request(path, raw_traceparent, rid)
        try:
            with telemetry.trace_scope(ctx):
                yield ctx
        finally:
            finish()

    def observe_request(
        self, transport: str, path: str, status: int, duration_ms: float
    ) -> None:
        """Record one transport-boundary request in the shared registry
        (both transports call this so the series are comparable)."""
        route = path if path in KNOWN_ROUTES else "<other>"
        self._m_http_requests.labels(transport, route, str(status)).inc()
        if status >= 400:
            self._m_http_errors.labels(transport, route).inc()
        self._h_http_ms.labels(transport, route).observe(duration_ms)

    def predict(self, payload: dict) -> Any:
        if self._draining:
            raise EngineUnavailable(
                "serving app is draining and not accepting requests",
                reason="draining", retry_after_s=1.0,
            )
        if self.model.artifact is None:
            self.setup_model()
        inputs = payload.get("inputs")
        features = payload.get("features")
        if (inputs is None) == (features is None):
            raise ValueError("provide exactly one of 'inputs' or 'features'")
        # the payload-contract per-request token cap: validated here
        # (422 on garbage) and opened as an ambient scope around the
        # dispatch, so an engine-backed predictor honors it without a
        # kwarg threading through every wrapper — and the cap survives
        # the router hop, which two-leg disaggregated dispatch needs
        # for token parity. Non-engine predictors ignore it.
        cap = validate_token_cap(payload.get("max_new_tokens"))
        if cap is not None and self._batcher is not None:
            # the micro-batcher dispatches full batches on its own
            # flush thread — a per-request cap cannot bind there, and
            # silently decoding to the default would break exactly the
            # cross-hop token parity the payload field exists for:
            # refuse loudly (→ 422) instead
            raise ValueError(
                "max_new_tokens is not supported on a batched "
                "(MicroBatcher) app — the batcher computes full "
                "batches in one device call; serve the engine "
                "directly for per-request caps"
            )
        with token_cap_scope(cap):
            if inputs is not None:
                return _to_jsonable(self.model.predict(**inputs))
            loaded = self.model.dataset.get_features(features)
            if self._batcher is not None:
                return _to_jsonable(self._batcher.submit(loaded))
            return _to_jsonable(
                self.model.predict_from_features_workflow()(
                    model_object=self.model.artifact.model_object,
                    features=loaded,
                )
            )

    def predict_stream(self, payload: dict):
        """Yield token chunks for ONE prompt (the SSE event source).

        ``{"features": [prompt]}`` (a single row, or a one-row list) —
        the reader-kwargs ``inputs`` form is not streamable because it
        runs the full predict workflow in one call.
        """
        if self._draining:
            raise EngineUnavailable(
                "serving app is draining and not accepting requests",
                reason="draining", retry_after_s=1.0,
            )
        if self._stream_fn is None:
            raise ValueError(
                "streaming is not enabled on this app — construct "
                "ServingApp(stream=...) with an engine-backed generator"
            )
        if self.model.artifact is None:
            self.setup_model()
        features = payload.get("features")
        if not features:
            raise ValueError(
                "streaming requires non-empty 'features' (a single "
                "token-id prompt or a one-element list of prompts)"
            )
        rows = features if isinstance(features[0], (list, tuple)) else [features]
        if len(rows) != 1:
            raise ValueError(
                f"streaming serves one prompt per request, got {len(rows)}"
            )
        loaded = self.model.dataset.get_features(rows)
        # same payload-contract cap as predict() — but a generator-
        # backed stream hook defers its body (where the engine reads
        # the ambient cap) to the FIRST next(), which happens after
        # this frame returns. The wrapper re-opens the scope around
        # exactly that first pull, so the cap binds for ANY caller of
        # this public method, not just predict_stream_events.
        cap = validate_token_cap(payload.get("max_new_tokens"))
        stream = self._stream_fn(self.model.artifact.model_object, loaded)
        if cap is None:
            return stream

        def capped():
            it = iter(stream)
            with token_cap_scope(cap):
                try:
                    first = next(it)
                except StopIteration:
                    return
            yield first
            yield from it

        return capped()

    def predict_stream_events(self, payload: dict):
        """The SSE wire protocol, shared by every transport: an iterator
        of pre-framed ``data: ...\\n\\n`` strings — one ``{"tokens"}``
        event per harvested chunk, then ``{"done", "n_tokens"}``.

        Validation raises BEFORE the first string exists (the first
        chunk is pulled eagerly here — generator-backed streams defer
        their checks to the first ``next()``, and those errors still
        deserve a 422 response, not a committed-then-dropped 200).
        The payload token cap binds inside :meth:`predict_stream`'s
        wrapper (its one home), which covers this eager pull too.
        """
        it = iter(self.predict_stream(payload))
        try:
            first = [next(it)]
        except StopIteration:
            first = []

        def frames():
            n = 0
            for chunk in itertools.chain(first, it):
                toks = _to_jsonable(chunk)
                n += len(toks)
                yield f"data: {json.dumps({'tokens': toks})}\n\n"
            yield f"data: {json.dumps({'done': True, 'n_tokens': n})}\n\n"

        return frames()

    # -- stdlib HTTP transport --------------------------------------------

    def _make_handler(self):
        app = self

        class Handler(BaseHTTPRequestHandler):
            # per-request telemetry, set by the do_* wrappers
            _rid = ""
            _status = 0
            _trace_ctx: Optional[telemetry.TraceContext] = None
            _tenant = DEFAULT_TENANT
            _priority = DEFAULT_PRIORITY
            _model_version = DEFAULT_MODEL_VERSION

            def log_message(self, fmt, *args):
                logger.info(f"http: {fmt % args}")

            def _send(self, code: int, body: Any, content_type="application/json",
                      extra_headers: Optional[dict] = None):
                data = (
                    body.encode() if isinstance(body, str) else json.dumps(body).encode()
                )
                self._status = code
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.send_header("X-Request-ID", self._rid)
                self.send_header("X-Tenant-ID", self._tenant)
                self.send_header("X-Priority", self._priority)
                self.send_header("X-Model-Version", self._model_version)
                if self._trace_ctx is not None:
                    self.send_header(
                        "traceparent",
                        telemetry.format_traceparent(self._trace_ctx),
                    )
                for name, value in (extra_headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(data)

            def _route(self):
                """``(path, query)`` with the query string split off —
                ``/debug/flight?n=5`` must route as ``/debug/flight``
                (and land in that metric series, not ``<other>``)."""
                parts = urlsplit(self.path)
                return parts.path, parse_qs(parts.query)

            def _observed(self, handler):
                """Wrap one request: mint the X-Request-ID, resolve the
                W3C trace context (predict routes open a recorded
                server timeline; everything else just echoes), time
                the dispatch, land the per-endpoint series."""
                self._rid = telemetry.new_request_id()
                self._status = 0
                path = self._route()[0]
                raw_tp = self.headers.get("traceparent")
                t0 = time.perf_counter()
                try:
                    try:
                        # validated at the boundary: a hostile tenant
                        # or priority header answers 422 before any
                        # route logic, and can never reach a label
                        # value or the scheduler
                        self._tenant = validate_tenant(
                            self.headers.get("X-Tenant-ID")
                        )
                        self._priority = validate_priority(
                            self.headers.get("X-Priority")
                        )
                        self._model_version = validate_model_version(
                            self.headers.get("X-Model-Version")
                        )
                    except ValueError as exc:
                        self._trace_ctx = telemetry.server_trace_context(
                            raw_tp
                        )
                        self._send(422, {"error": str(exc)})
                        return
                    # method-checked: a GET probe/scan of /predict 404s
                    # without opening a recorded timeline, so probes
                    # can never churn the trace ring or the OTLP queue
                    if path in TRACED_ROUTES and self.command == "POST":
                        # the timeline is keyed by the response's
                        # X-Request-ID, so /debug/trace?rid= answers
                        # with the id the client actually holds
                        with app.traced_request(
                            path, raw_tp, rid=self._rid
                        ) as ctx:
                            self._trace_ctx = ctx
                            # visible to engine/batcher submissions on
                            # this request thread (deadline-scope-style)
                            with tenant_scope(self._tenant), \
                                    priority_scope(self._priority), \
                                    model_version_scope(
                                        self._model_version):
                                handler()
                    else:
                        self._trace_ctx = telemetry.server_trace_context(raw_tp)
                        handler()
                finally:
                    app.observe_request(
                        "stdlib", path, self._status or 500,
                        (time.perf_counter() - t0) * 1e3,
                    )

            def do_GET(self):
                self._observed(self._get)

            def do_POST(self):
                self._observed(self._post)

            def _get(self):
                path, query = self._route()
                if path == "/":
                    self._send(200, app.root(), content_type="text/html")
                elif path == "/health":
                    h = app.health()
                    self._send(app.health_status(h), h)
                elif path == "/stats":
                    self._send(200, app.stats())
                elif path == "/metrics":
                    self._send(
                        200, app.metrics_text(),
                        content_type=telemetry.EXPOSITION_CONTENT_TYPE,
                    )
                elif path == "/debug/memory":
                    try:
                        self._send(200, app.debug_memory())
                    except Exception as exc:
                        self._send(500, {"error": str(exc)})
                elif path == "/debug/flight":
                    try:
                        n = (
                            int(query["n"][0]) if "n" in query else None
                        )
                        kind = query.get("kind", [None])[0]
                        rid = query.get("rid", [None])[0]
                        tenant = query.get("tenant", [None])[0]
                        phase = query.get("phase", [None])[0]
                    except (ValueError, IndexError) as exc:
                        self._send(422, {"error": f"bad query: {exc}"})
                        return
                    self._send(200, app.debug_flight(
                        n=n, kind=kind, rid=rid, tenant=tenant,
                        phase=phase,
                    ))
                elif path == "/debug/usage":
                    try:
                        self._send(200, app.debug_usage())
                    except ValueError as exc:
                        self._send(422, {"error": str(exc)})
                elif path == "/debug/cache/peek":
                    try:
                        self._send(200, app.debug_cache_peek(
                            query.get("prompt", [""])[0]
                        ))
                    except (ValueError, TypeError) as exc:
                        self._send(422, {"error": str(exc)})
                elif path == "/debug/trace":
                    fmt = query.get("format", ["chrome"])[0]
                    try:
                        body, content_type = app.debug_trace(
                            fmt,
                            rid=query.get("rid", [None])[0],
                            trace=query.get("trace", [None])[0],
                        )
                    except ValueError as exc:
                        self._send(422, {"error": str(exc)})
                        return
                    self._send(200, body, content_type=content_type)
                elif path == "/debug/slo":
                    try:
                        self._send(200, app.debug_slo())
                    except ValueError as exc:
                        self._send(422, {"error": str(exc)})
                elif path == "/debug/fleet":
                    try:
                        self._send(200, app.debug_fleet())
                    except ValueError as exc:
                        self._send(422, {"error": str(exc)})
                elif path == "/debug/rollout":
                    try:
                        self._send(200, app.debug_rollout())
                    except ValueError as exc:
                        self._send(422, {"error": str(exc)})
                elif path == "/debug/goodput":
                    try:
                        self._send(200, app.debug_goodput())
                    except ValueError as exc:
                        self._send(422, {"error": str(exc)})
                elif path == "/debug/tail":
                    try:
                        self._send(200, app.debug_tail(
                            metric=query.get("metric", [""])[0],
                            n=(
                                int(query["n"][0])
                                if "n" in query else None
                            ),
                        ))
                    except ValueError as exc:
                        self._send(422, {"error": str(exc)})
                else:
                    self._send(404, {"error": f"no route {path}"})

            def _send_sse(self, frames):
                """Stream pre-framed SSE strings; the connection closes
                at end-of-stream (no Content-Length — ``Connection:
                close`` delimits the body for HTTP/1.x clients). Once
                the 200 is committed, a mid-stream failure can only
                surface as a dropped connection — the SSE contract —
                never as a second response spliced into the body."""
                self._status = 200
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.send_header("X-Request-ID", self._rid)
                self.send_header("X-Tenant-ID", self._tenant)
                self.send_header("X-Priority", self._priority)
                self.send_header("X-Model-Version", self._model_version)
                if self._trace_ctx is not None:
                    self.send_header(
                        "traceparent",
                        telemetry.format_traceparent(self._trace_ctx),
                    )
                self.end_headers()
                try:
                    for frame in frames:
                        self.wfile.write(frame.encode())
                        self.wfile.flush()
                except BrokenPipeError:
                    pass  # client went away: the engine's generator
                    # cleanup (GeneratorExit → abandoned) stops the work
                except Exception as exc:
                    logger.info(f"stream aborted mid-flight: {exc!r}")
                finally:
                    self.close_connection = True

            def _post(self):
                path, query = self._route()
                if path == "/debug/profile":
                    self._debug_profile(query)
                    return
                if path in ("/debug/kv/export", "/debug/kv/import"):
                    self._debug_kv(path)
                    return
                if path not in ("/predict", "/predict/stream"):
                    self._send(404, {"error": f"no route {path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    try:
                        payload = json.loads(self.rfile.read(length) or b"{}")
                    except json.JSONDecodeError as exc:
                        self._send(422, {"error": f"request body must be JSON: {exc}"})
                        return
                    try:
                        deadline_ms = parse_deadline_header(
                            self.headers.get("X-Deadline-Ms")
                        )
                    except ValueError as exc:
                        self._send(422, {"error": str(exc)})
                        return
                    # the scope makes the deadline visible to engine/
                    # batcher submissions on this request thread without
                    # threading a kwarg through every predictor wrapper
                    with deadline_scope(deadline_ms):
                        if path == "/predict/stream":
                            # predict_stream_events validates (and pulls
                            # the first chunk) BEFORE this point commits
                            # a 200 — errors still get a whole 4xx/5xx
                            self._send_sse(app.predict_stream_events(payload))
                        else:
                            self._send(200, app.predict(payload))
                except (Overloaded, EngineUnavailable, DeadlineExceeded) as exc:
                    # typed load shed: the faults.http_fault_response
                    # contract (429/503 + Retry-After, 504) both
                    # transports share
                    status, extra = http_fault_response(exc)
                    body = {"error": str(exc)}
                    if isinstance(exc, EngineUnavailable):
                        body["reason"] = exc.reason
                    self._send(status, body, extra_headers=extra or None)
                except (ValueError, KeyError, TypeError) as exc:
                    self._send(422, {"error": str(exc)})
                except Exception as exc:  # unexpected: surface as 500
                    logger.info(f"predict error: {exc!r}")
                    self._send(500, {"error": str(exc)})

            def _debug_kv(self, path):
                """POST /debug/kv/export | /debug/kv/import — the
                cross-host KV handoff surface (JSON body either way;
                422 on an unwired hook or malformed body)."""
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    try:
                        payload = json.loads(self.rfile.read(length) or b"{}")
                    except json.JSONDecodeError as exc:
                        self._send(
                            422,
                            {"error": f"request body must be JSON: {exc}"},
                        )
                        return
                    if not isinstance(payload, dict):
                        # `[]`/`"x"` parse as JSON but aren't the
                        # object contract — 422 like the FastAPI
                        # transport's `payload: dict` coercion, never
                        # a 500 from payload.get
                        self._send(
                            422,
                            {"error": "request body must be a JSON "
                                      "object"},
                        )
                        return
                    if path == "/debug/kv/export":
                        self._send(200, app.debug_kv_export(
                            payload.get("prompt") or []
                        ))
                    else:
                        self._send(200, app.debug_kv_import(
                            payload.get("entries")
                        ))
                except (ValueError, KeyError, TypeError) as exc:
                    self._send(422, {"error": str(exc)})
                except Exception as exc:
                    logger.info(f"kv handoff error: {exc!r}")
                    self._send(500, {"error": str(exc)})

            def _debug_profile(self, query):
                """POST /debug/profile?seconds=N (or a {"seconds": N}
                JSON body): blocking on-demand profiler capture. 409
                while another capture runs — the profiler is a
                process-global singleton."""
                from unionml_tpu.introspection import ProfileInProgress

                try:
                    seconds = None
                    if "seconds" in query:
                        seconds = float(query["seconds"][0])
                    else:
                        length = int(self.headers.get("Content-Length", 0))
                        if length:
                            body = json.loads(self.rfile.read(length))
                            if "seconds" in body:
                                seconds = float(body["seconds"])
                    result = app.debug_profile(
                        **({} if seconds is None else {"seconds": seconds})
                    )
                    self._send(200, result)
                except ProfileInProgress as exc:
                    self._send(409, {"error": str(exc)})
                except (ValueError, TypeError, json.JSONDecodeError) as exc:
                    self._send(422, {"error": str(exc)})
                except Exception as exc:
                    logger.info(f"profile capture error: {exc!r}")
                    self._send(500, {"error": str(exc)})

        return Handler

    def serve(self, host: str = "127.0.0.1", port: int = 8000, *, blocking: bool = True):
        """Start the HTTP server; ``blocking=False`` runs it on a thread and
        returns the bound ``(host, port)``."""
        self.setup_model()
        self._server = ThreadingHTTPServer((host, port), self._make_handler())
        bound = self._server.server_address
        logger.info(f"serving {self.model.name} on http://{bound[0]}:{bound[1]}")
        if blocking:
            try:
                self._server.serve_forever()
            except KeyboardInterrupt:
                pass
            finally:
                self._server.server_close()
        else:
            thread = threading.Thread(target=self._server.serve_forever, daemon=True)
            thread.start()
        return bound

    def shutdown(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._batcher is not None:
            self._batcher.close()
            self._batcher = None
        if self._otlp is not None:
            self._otlp.close()
            self._otlp = None
        if self._slo is not None:
            self._slo.stop()


def create_app(model, **kwargs) -> ServingApp:
    """Build a :class:`ServingApp` for ``model`` (the dependency-free analog
    of mounting routes on a FastAPI app)."""
    return ServingApp(model, **kwargs)
