"""Disaggregated prefill/decode serving: phase-split engine pools with
cross-engine KV block handoff.

Colocated serving makes prefill and decode fight for the same engine:
a long prompt's chunked prefill occupies the single admission lane and
steals dispatcher passes, so every resident decode stream — and every
short prompt queued behind it — stalls for the duration. The standard
production fix (DistServe, Zhong et al. 2024; Splitwise, Patel et al.
2024) splits the two phases onto SEPARATE engine pools: prefill
engines absorb the long, bursty prompt work; decode engines keep a
steady token-streaming cadence; the prompt's KV crosses between them.

This module is that architecture built from seams the stack already
has — the KV handoff IS the prefix-cache machinery:

- the **prefill leg** is a 1-token admission
  (:meth:`~unionml_tpu.serving.engine.DecodeEngine.prefill_export`):
  the engine runs the prompt's (chunked) prefill through its normal
  admission path, the harvest finalizes the prompt's full KV blocks
  into the host prefix-cache block store (the same extract/insert
  every admission performs — pointer handoff, no extra copies), a
  :class:`~unionml_tpu.serving.prefix_cache.PrefixLease` pins the
  exported path, and the sampled first token comes back as the
  caller's TTFT emission;
- the **decode leg** is a normal streaming admission on a decode
  engine: its prefix-cache match finds the handed-off blocks and
  SPLICES them (the warm-hit path), prefilling only the uncovered
  tail — then decodes with tokens bit-identical to the colocated run
  (the same determinism the router's mid-stream failover rides). The
  first token, regenerated deterministically, is replay-skipped.
- **same-host pools share one host block store** (construct both
  engines with the same :class:`~unionml_tpu.serving.prefix_cache
  .RadixPrefixCache`): the handoff costs zero bytes. **Cross-host**,
  the router pulls the prefill replica's entries over
  ``POST /debug/kv/export`` and pushes them into the decode replica
  over ``POST /debug/kv/import`` (wire-encoded blocks; see
  :func:`~unionml_tpu.serving.prefix_cache.encode_entries`).

Because the handoff is a CACHE transaction, the robustness story is
structural, not bolted on: a prefill replica dying between export and
splice — or a failed transfer, or a store that evicted the blocks —
just means the decode leg's match comes up short and it re-prefills
the difference. **Degrade, never error**: the caller sees identical
tokens either way. Both legs ride the full
:class:`~unionml_tpu.serving.router.FleetRouter` envelope (retries,
budgets, ejection, mid-stream failover); the handle's lease releases
exactly once (idempotent) in a ``finally``, so retries and hedges can
neither double-bill nor leak pins; and short prompts — for which
colocated serving still wins (docs/serving.md) — bypass the prefill
pool entirely below ``handoff_min_tokens``.

Observability: both legs' pick/attempt spans land under ONE routing
rid, joined by ``prefill-leg`` → ``handoff`` → ``decode-leg`` spans
(``GET /debug/trace?rid=`` stitches them, replica server spans
included); flight ``handoff`` events carry both pools' phase tags;
``unionml_disagg_*`` series count legs, handoff outcomes, transferred
blocks, and per-pool membership.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Iterator, List, Optional, Sequence

from unionml_tpu._logging import logger
from unionml_tpu.serving.faults import DeadlineExceeded, EngineUnavailable
from unionml_tpu.serving.router import (
    _EJECTED,
    _HALF_OPEN,
    _LIVE,
    FleetRouter,
    ReplicaHandle,
    _TracedStream,
)
from unionml_tpu.serving.scheduler import PHASES

__all__ = ["DisaggRouter", "HANDOFF_RESULTS", "PHASES"]

# CLOSED handoff-outcome set (the unionml_disagg_handoffs_total{result}
# label): shared = same host store, pointer handoff; transfer = blocks
# crossed stores; cold = nothing usable arrived (the decode leg
# re-prefills — the degrade arm); skipped = transfer disabled.
HANDOFF_RESULTS = ("shared", "transfer", "cold", "skipped")

# CLOSED request-path set (unionml_disagg_requests_total{path}):
# two_leg = prefill pool + decode pool; single_leg = decode pool only
# (short prompt, or no prefill pool routable); degraded = a two-leg
# attempt whose prefill leg failed and fell back to a cold decode-side
# prefill (zero caller-visible failures by construction).
REQUEST_PATHS = ("two_leg", "single_leg", "degraded")


_phase_tls = threading.local()


@contextmanager
def _dispatch_phase(phase: Optional[str]) -> Iterator[None]:
    """Constrain picks on this thread to ``phase``-capable replicas
    (colocated replicas serve either phase). Thread-local like the
    router's rid scope: each leg's whole retry envelope — repeat
    picks included — stays inside its pool."""
    prev = getattr(_phase_tls, "phase", None)
    _phase_tls.phase = phase
    try:
        yield
    finally:
        _phase_tls.phase = prev


def _current_dispatch_phase() -> Optional[str]:
    return getattr(_phase_tls, "phase", None)


class DisaggRouter(FleetRouter):
    """A :class:`~unionml_tpu.serving.router.FleetRouter` whose
    generative dispatch is phase-split (module docstring has the full
    story): replicas tagged ``phase="prefill"`` form the prefill pool,
    ``phase="decode"`` the decode pool, and ``colocated`` replicas
    serve either leg. Everything else — membership, health, ejection,
    drain/join, hedge policy knobs, ``make_router_app`` — is inherited
    unchanged, so the disaggregated front door mounts on both HTTP
    transports exactly like the plain router.

    Args:
        replicas: the fleet. At least one decode-capable replica
            (``decode`` or ``colocated``) is required — the decode
            pool is where streams live; a fleet with no DEDICATED
            prefill replica degrades to plain colocated routing.
        handoff_min_tokens: prompts SHORTER than this dispatch as one
            leg on the decode pool (colocated still wins for short
            prompts: the handoff round trip costs more than the
            prefill it saves — docs/serving.md derives the
            crossover). ``None`` sends every prompt two-leg.
        transfer: move KV entries between DISTINCT host stores (the
            cross-host ``/debug/kv/export``→``/debug/kv/import`` hop,
            or pointer imports between in-process stores). ``False``
            limits warm handoff to pools sharing one store; distinct
            stores then decode from a cold prefill (correct, slower).
        **kwargs: forwarded to :class:`FleetRouter` (policy, telemetry
            sinks, clock).
    """

    def __init__(
        self,
        replicas: Sequence[ReplicaHandle],
        *,
        handoff_min_tokens: Optional[int] = None,
        transfer: bool = True,
        **kwargs,
    ):
        if handoff_min_tokens is not None and handoff_min_tokens < 1:
            raise ValueError(
                f"handoff_min_tokens must be >= 1 when set, got "
                f"{handoff_min_tokens}"
            )
        if not any(
            getattr(r, "phase", "colocated") in ("decode", "colocated")
            for r in replicas
        ):
            raise ValueError(
                "DisaggRouter needs at least one decode-capable replica "
                "(phase='decode' or 'colocated') — streams live on the "
                "decode pool; a prefill-only fleet cannot serve"
            )
        self.handoff_min_tokens = handoff_min_tokens
        self.transfer = bool(transfer)
        super().__init__(replicas, **kwargs)
        self._sync_pool_gauges()

    # -- instruments -------------------------------------------------------

    def _build_instruments(self) -> None:
        super()._build_instruments()
        reg = self._registry
        self._m_disagg_requests = reg.counter(
            "unionml_disagg_requests_total",
            "Generative requests through the disaggregated router, by "
            "dispatch path (two_leg / single_leg / degraded — degraded "
            "= the prefill leg failed and the decode pool prefilled "
            "cold; never a caller-visible error).",
            ("path",),
        )
        self._m_handoffs = reg.counter(
            "unionml_disagg_handoffs_total",
            "KV handoffs between the prefill and decode legs, by "
            "outcome (shared = one host store, pointer handoff; "
            "transfer = entries crossed stores; cold = decode "
            "re-prefilled; skipped = transfer disabled).",
            ("result",),
        )
        self._m_kv_blocks = reg.counter(
            "unionml_disagg_kv_blocks_transferred_total",
            "Prefix-cache blocks moved between distinct host stores by "
            "the KV handoff (shared-store handoffs move pointers, not "
            "blocks, and count zero here).",
        )
        self._h_handoff_ms = reg.histogram(
            "unionml_disagg_handoff_ms",
            "KV handoff wall time (store-identity check + any "
            "cross-store export/import) between the legs.",
        )
        self._g_pool = reg.gauge(
            "unionml_disagg_pool_replicas",
            "Registered replicas per serving phase (membership, not "
            "routability — the per-pool fleet-size view).",
            ("phase",),
        )

    def _sync_pool_gauges(self) -> None:
        with self._lock:
            counts = {p: 0 for p in PHASES}
            for s in self._replicas.values():
                counts[getattr(s.handle, "phase", "colocated")] += 1
        for p, c in counts.items():
            self._g_pool.labels(p).set(float(c))

    def add_replica(self, handle: ReplicaHandle) -> None:
        super().add_replica(handle)
        self._sync_pool_gauges()

    def remove_replica(self, name: str, *, drain_timeout: float = 30.0) -> bool:
        out = super().remove_replica(name, drain_timeout=drain_timeout)
        self._sync_pool_gauges()
        return out

    # -- phase-aware picking ----------------------------------------------

    def _pick(
        self, prompt: Sequence[int], exclude: Sequence[str] = (),
        **kw,
    ) -> ReplicaHandle:
        """The inherited scored pick, constrained to the ambient leg's
        pool: replicas of the OTHER dedicated phase are excluded
        (colocated replicas serve either leg). The exclusion is
        re-derived on every call, so the envelope's repeat-pick
        fallback can never leak a decode stream onto the prefill
        pool. Version constraints (``version=``/``version_soft=``,
        docs/robustness.md "Rollouts & rollback") pass through to the
        base pick and compose with the phase filter."""
        phase = _current_dispatch_phase()
        if phase is not None:
            with self._lock:
                wrong = [
                    n for n, s in self._replicas.items()
                    if getattr(s.handle, "phase", "colocated")
                    not in (phase, "colocated")
                ]
            if wrong:
                exclude = list(exclude) + wrong
        return super()._pick(prompt, exclude=exclude, **kw)

    def _has_routable_phase(self, phase: str) -> bool:
        """Does a DEDICATED ``phase`` replica look routable right now?
        (Membership-level peek, same states a pick would consider —
        decides whether a two-leg dispatch is worth attempting.)"""
        now = self._clock()
        with self._lock:
            for s in self._replicas.values():
                if getattr(s.handle, "phase", "colocated") != phase:
                    continue
                if s.state == _LIVE:
                    return True
                if s.state == _EJECTED and now >= s.rejoin_at:
                    return True
                if s.state == _HALF_OPEN and not s.probe_inflight:
                    return True
        return False

    # -- the two-leg dispatch ---------------------------------------------

    def generate_stream(
        self, prompt: Sequence[int], *, max_new_tokens: Optional[int] = None,
    ) -> Iterator[List[int]]:
        """Stream token chunks through the phase-split pipeline: the
        prefill leg's first token arrives as soon as the prefill pool
        finishes the prompt (the TTFT the architecture exists for),
        then the decode leg streams the rest from spliced KV. Short
        prompts (< ``handoff_min_tokens``) and fleets without a
        prefill pool dispatch as a single decode-pool leg. Every exit
        releases the handle's lease exactly once."""
        if self._draining:
            raise EngineUnavailable(
                "router is draining", reason="draining",
            )
        self._deposit_budget()
        # resolved once on the caller's thread: BOTH legs of a pinned/
        # split request must land on the same model version, or the
        # decode leg would splice KV produced under different weights
        version, version_soft, excl_version = self._resolve_route_version()
        rid, t_ctx, tracer = self._open_timeline(len(prompt))
        inner = self._two_leg_stream(
            rid, [int(t) for t in prompt], max_new_tokens, t_ctx, tracer,
            version, version_soft, excl_version,
        )
        if t_ctx is None:
            return inner
        return _TracedStream(tracer, rid, inner)

    def generate(
        self, prompt: Sequence[int], *, max_new_tokens: Optional[int] = None,
    ) -> List[int]:
        """Blocking collect over :meth:`generate_stream` — the two-leg
        pipeline is streaming-first (the first token IS the handoff
        boundary), so the blocking surface rides it. Hedging, a
        blocking-only optimization on the base router, does not apply
        to phase-split dispatch; each leg still gets the full retry
        envelope."""
        return self._collect(
            self.generate_stream(prompt, max_new_tokens=max_new_tokens)
        )

    def _two_leg_stream(self, rid, prompt, max_new_tokens, t_ctx, tracer,
                        version=None, version_soft=True,
                        exclude_version=None):
        handle: Optional[dict] = None
        prefill_replica: Optional[ReplicaHandle] = None
        emitted = 0
        path = "single_leg"
        # the handle's ONE home for lease accounting: prefill_dispatch
        # stores it here the moment the export succeeds, BEFORE the
        # TTFT token is yielded — so a caller closing the stream right
        # after its first chunk (GeneratorExit at the yield) still
        # reaches the finally with the live lease in hand. The local
        # `handle` below is only the transfer-decision view.
        box: dict = {}
        want_two_leg = self._has_routable_phase("prefill") and (
            self.handoff_min_tokens is None
            or len(prompt) >= self.handoff_min_tokens
        )
        try:
            if want_two_leg:
                path = "two_leg"

                def prefill_dispatch(replica):
                    h = replica.prefill_export(
                        prompt, max_new_tokens=max_new_tokens,
                    )
                    box["handle"] = h
                    box["replica"] = replica
                    return iter([[int(t) for t in h["tokens"]]])

                t_leg0 = time.perf_counter()
                try:
                    with _dispatch_phase("prefill"):
                        for chunk in self._stream_with_failover(
                            rid, prompt, max_new_tokens=max_new_tokens,
                            dispatch=prefill_dispatch, t_ctx=t_ctx,
                            tracer=tracer, version=version,
                            version_soft=version_soft,
                            exclude_version=exclude_version,
                            # a prefill leg's 1-token result is not a
                            # full answer: never offer it for shadowing
                            notify_rollout=False,
                        ):
                            emitted += len(chunk)
                            yield chunk  # the TTFT emission
                    handle = box.get("handle")
                    prefill_replica = box.get("replica")
                    if tracer is not None:
                        tracer.record_span(
                            rid, "prefill-leg", t_leg0,
                            time.perf_counter(),
                            replica=getattr(
                                prefill_replica, "name", None
                            ),
                            tokens=emitted,
                        )
                except GeneratorExit:
                    raise  # caller abandoned: never mask it
                except Exception as exc:
                    if isinstance(exc, (ValueError, DeadlineExceeded)):
                        # the caller's own fault, deterministically:
                        # a bad request fails identically on every
                        # replica and an expired deadline arrives just
                        # as expired — a second dispatch is doomed
                        # work wearing a "degraded" label. Surface it.
                        raise
                    # the prefill POOL failed this request's leg after
                    # its whole retry envelope (infra-class errors
                    # only): DEGRADE — the decode pool prefills cold
                    # and the caller never sees an error. Tokens
                    # already emitted (a leg that died after its
                    # single yield) are replay-skipped below exactly
                    # like mid-stream failover.
                    path = "degraded"
                    handle = None
                    if tracer is not None:
                        tracer.record_span(
                            rid, "prefill-leg", t_leg0,
                            time.perf_counter(),
                            outcome="degraded",
                            error=type(exc).__name__,
                        )
                    self._flight.record(
                        "handoff", rid=rid, result="cold",
                        degraded=True, error=type(exc).__name__,
                        phases=["prefill", "decode"],
                    )
                    logger.info(
                        f"disagg: prefill leg failed ({exc!r}); "
                        "decode pool prefills cold"
                    )
                if (
                    handle is not None
                    and max_new_tokens is not None
                    and emitted >= int(max_new_tokens)
                ):
                    return  # 1-token request: the prefill leg IS the answer

            def decode_dispatch(replica):
                if handle is not None:
                    self._handoff(
                        rid, tracer, prefill_replica, replica, handle,
                        prompt,
                    )
                return replica.generate_stream(
                    prompt, max_new_tokens=max_new_tokens,
                )

            t_leg1 = time.perf_counter()
            skip = emitted
            with _dispatch_phase("decode"):
                for chunk in self._stream_with_failover(
                    rid, prompt, max_new_tokens=max_new_tokens,
                    dispatch=decode_dispatch, t_ctx=t_ctx, tracer=tracer,
                    version=version, version_soft=version_soft,
                    exclude_version=exclude_version,
                ):
                    # the decode engine deterministically regenerates
                    # the first token(s) the prefill leg already
                    # emitted — drop them, the failover replay-skip
                    # discipline applied across legs
                    if skip >= len(chunk):
                        skip -= len(chunk)
                        continue
                    out = chunk[skip:] if skip else chunk
                    skip = 0
                    yield out
            if tracer is not None and (want_two_leg or path == "single_leg"):
                tracer.record_span(
                    rid, "decode-leg", t_leg1, time.perf_counter(),
                )
        finally:
            self._m_disagg_requests.labels(path).inc()
            exported = box.get("handle")
            if exported is not None:
                lease = exported.get("lease")
                if lease is not None:
                    # exactly-once by idempotence: retries, degrades,
                    # error exits, AND a caller abandoning the stream
                    # mid-leg all funnel here — the exported path
                    # unpins once the stream is over, however it ended
                    lease.release()

    def _handoff(
        self, rid, tracer, src: Optional[ReplicaHandle],
        dst: ReplicaHandle, handle: dict, prompt: Sequence[int],
    ) -> None:
        """Make the prefill leg's KV reachable from ``dst`` before its
        dispatch: same-store pools need nothing (pointer handoff);
        distinct stores move entries (in-process: pointer imports;
        cross-host: the ``/debug/kv/export``→``/debug/kv/import``
        wire hop). Runs per decode ATTEMPT, so a failover survivor is
        warmed too. Every failure degrades to a cold decode-side
        prefill — this method never raises."""
        t0 = time.perf_counter()
        result, blocks = "cold", 0
        try:
            src_store = src.kv_store() if src is not None else None
            if src_store is not None and src_store is dst.kv_store():
                result = "shared"
            elif not self.transfer:
                result = "skipped"
            elif src is not None:
                if (
                    hasattr(src, "_kv_export_wire")
                    and hasattr(dst, "_kv_import_wire")
                ):
                    # remote→remote: relay the wire form untouched —
                    # decoding megabytes of KV into numpy only to
                    # re-encode the identical bytes is pure churn on
                    # the handoff critical path (and it re-runs per
                    # decode failover attempt)
                    blocks = int(dst._kv_import_wire(
                        src._kv_export_wire(prompt)
                    ))
                else:
                    entries = src.export_request_blocks(prompt)
                    if entries:
                        blocks = int(dst.import_cache_blocks(entries))
                # "transfer" means blocks actually LANDED: a donor
                # with entries whose importer attached nothing (byte
                # budget) is a cold decode in practice, and the label
                # exists to surface exactly that
                if blocks > 0:
                    result = "transfer"
        except Exception as exc:
            result = "cold"
            logger.info(
                f"disagg: KV transfer to {dst.name} failed ({exc!r}); "
                "decode leg prefills cold"
            )
        self._m_handoffs.labels(result).inc()
        if blocks:
            self._m_kv_blocks.inc(blocks)
        now = time.perf_counter()
        # exemplar-tagged: /debug/tail?metric=unionml_disagg_handoff_ms
        # resolves a slow handoff straight to its stitched timeline
        self._h_handoff_ms.observe((now - t0) * 1e3, exemplar=rid)
        self._flight.record(
            "handoff", rid=rid, result=result, blocks=blocks,
            handoff_ms=round((now - t0) * 1e3, 3),
            prefill_replica=getattr(src, "name", None),
            decode_replica=dst.name,
            cached_tokens=int(handle.get("cached_tokens", 0) or 0),
            phases=["prefill", "decode"],
        )
        if tracer is not None:
            tracer.record_span(
                rid, "handoff", t0, now, result=result, blocks=blocks,
                replica=dst.name,
            )
