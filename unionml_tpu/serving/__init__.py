"""Serving: HTTP endpoints + on-device micro-batching.

Reference counterpart: unionml/fastapi.py (FastAPI-only, per-request
predictor call). The TPU-native redesign has two layers:

- :mod:`unionml_tpu.serving.batcher` — a micro-batcher that coalesces
  concurrent requests into one padded, bucketed device call (XLA compiles
  one executable per bucket; p50 latency amortizes MXU dispatch).
- :mod:`unionml_tpu.serving.engine` — a continuous-batching decode
  engine for LLM serving: fixed resident slots, per-slot KV fill,
  requests join/retire at chunk boundaries instead of waiting out the
  in-flight generation (the 8-client p95 fix).
- :mod:`unionml_tpu.serving.prefix_cache` — automatic cross-request
  prompt-prefix reuse: a radix tree of KV blocks in a byte-budgeted
  host store; engine admissions splice the longest cached prefix and
  prefill only the uncovered suffix (docs/prefix_caching.md).
- transport: :mod:`unionml_tpu.serving.http` is a dependency-free stdlib
  HTTP server with the same surface (``GET /``, ``POST /predict``,
  ``GET /health``, ``GET /stats``, Prometheus ``GET /metrics``);
  :mod:`unionml_tpu.serving.fastapi` mounts the identical routes on a
  FastAPI app when that stack is installed.

Both engines, both transports, and the step trainer publish through the
:mod:`unionml_tpu.telemetry` registry — one ``GET /metrics`` scrape
covers every layer, and engine requests record Perfetto-exportable
trace spans (docs/observability.md). The distributed half: transports
parse/echo W3C ``traceparent`` headers and open a
:func:`~unionml_tpu.telemetry.trace_scope` so engine/batcher spans
join the caller's trace, an OTLP/HTTP exporter
(:mod:`unionml_tpu.exporters`) pushes spans + metric snapshots to a
collector, and an SLO watchdog (:mod:`unionml_tpu.slo`) evaluates
burn-rate objectives against the live registry, feeding
``GET /health`` → ``degraded``. The introspection layer
(:mod:`unionml_tpu.introspection`) adds hardware truth on top: per-
program XLA cost analysis with live MFU/roofline gauges, on-demand
profiler capture (``POST /debug/profile``), a device-memory breakdown
(``GET /debug/memory``), and a request flight recorder
(``GET /debug/flight``) whose snapshots make recoveries explainable
after the fact.

Fault tolerance (:mod:`unionml_tpu.serving.faults`,
docs/robustness.md): bounded queues and per-request deadlines shed load
with typed errors the transports map to 429/503/504 (+ ``Retry-After``),
the engine supervises itself — a failed device program fails only its
poisoned batch, rebuilds, and trips a circuit breaker if rebuilds keep
failing — ``drain()`` finishes in-flight streams for graceful
shutdown, and a deterministic :class:`~unionml_tpu.serving.faults
.FaultInjector` makes every failure mode reproducible in CPU-only
tests.

Usage metering (:mod:`unionml_tpu.serving.usage`, docs/observability.md
"Usage metering & cost attribution"): a :class:`~unionml_tpu.serving
.usage.UsageLedger` assembles a per-request resource vector — queue
wait, prefill vs. prefix-cache-saved tokens, decode tokens, attributed
device-seconds/FLOPs (per-dispatch cost split across the shared batch
by token share), KV block-seconds — billed to the ``X-Tenant-ID``
tenant the transports propagate via :func:`~unionml_tpu.serving.usage
.tenant_scope`. Per-tenant aggregates export as bounded-cardinality
``unionml_tenant_*`` series (top-K + ``other`` rollup) and the exact
vectors serve at ``GET /debug/usage`` — the measurement substrate for
per-tenant quotas and fair scheduling.

Preemptive scheduling (:mod:`unionml_tpu.serving.scheduler`,
docs/robustness.md "Preemption & fairness"): every engine admission
drains a priority-aware waiting room — per-(priority, tenant)
deficit-weighted queues fed by the usage ledger's fair shares, with the
``X-Priority`` header carried end to end like ``X-Tenant-ID`` — and on
a paged engine with a prefix cache the scheduler acts under pool
pressure: a strictly lower-priority resident's KV blocks are evicted
to the host block store and the stream resumed later via the splice
path with exact token parity, so one bulk tenant can no longer stall
every other caller behind a full pool.

Above all of it sits the cluster front door
(:mod:`unionml_tpu.serving.router`, docs/robustness.md "Fleet
robustness"): a :class:`~unionml_tpu.serving.router.FleetRouter`
fronts N engine replicas — picking by prefix-cache locality, queue
depth/breaker state, and SLO burn — and wraps every dispatch in a
robustness envelope (budgeted retries with backoff + ``Retry-After``,
optional tail-latency hedging, passive outlier ejection with half-open
rejoin, drain/join choreography), so a replica loss, hang, or drain is
invisible to callers. :func:`~unionml_tpu.serving.router
.make_router_app` mounts it on either transport.

Disaggregated prefill/decode serving
(:mod:`unionml_tpu.serving.disagg`, docs/serving.md "Disaggregated
serving"): a :class:`~unionml_tpu.serving.disagg.DisaggRouter` splits
the fleet into a prefill pool and a decode pool (DistServe/Splitwise
lineage) — a routed request prefills on a prefill replica, its KV
blocks cross to a decode replica through the prefix-cache block
machinery (shared host store same-host, ``/debug/kv/export``/
``/debug/kv/import`` cross-host), and the decode leg splices them and
streams with tokens bit-identical to the colocated run. Long prompts
stop stalling resident decode lanes; short prompts keep the colocated
fast path.
"""

from unionml_tpu.serving.autoscaler import (
    AutoscalerPolicy,
    EngineReplicaProvisioner,
    FleetAutoscaler,
    HttpReplicaProvisioner,
    ReplicaProvisioner,
)
from unionml_tpu.serving.batcher import MicroBatcher
from unionml_tpu.serving.disagg import DisaggRouter
from unionml_tpu.serving.engine import DecodeEngine
from unionml_tpu.serving.faults import (
    DeadlineExceeded,
    EngineUnavailable,
    FaultInjector,
    Overloaded,
    deadline_scope,
)
from unionml_tpu.serving.http import ServingApp, create_app
from unionml_tpu.serving.kv_pool import KVBlockPool, PoolExhausted
from unionml_tpu.serving.prefix_cache import RadixPrefixCache
from unionml_tpu.serving.router import (
    EngineReplica,
    FleetRouter,
    HttpReplica,
    ReplicaHandle,
    RouterPolicy,
    make_router_app,
)
from unionml_tpu.serving.scheduler import (
    PHASES,
    PRIORITIES,
    PreemptiveScheduler,
    SchedulerConfig,
    WaitingRoom,
    current_priority,
    priority_scope,
    token_cap_scope,
    validate_phase,
    validate_priority,
)
from unionml_tpu.serving.usage import (
    UsageLedger,
    current_tenant,
    tenant_scope,
    validate_tenant,
)

__all__ = [
    "AutoscalerPolicy", "DeadlineExceeded", "DecodeEngine",
    "DisaggRouter", "EngineReplica", "EngineReplicaProvisioner",
    "EngineUnavailable", "FaultInjector", "FleetAutoscaler",
    "FleetRouter", "HttpReplica", "HttpReplicaProvisioner",
    "KVBlockPool", "MicroBatcher", "Overloaded", "PHASES", "PRIORITIES",
    "PoolExhausted", "PreemptiveScheduler", "RadixPrefixCache",
    "ReplicaHandle", "ReplicaProvisioner", "RouterPolicy",
    "SchedulerConfig", "ServingApp", "UsageLedger", "WaitingRoom",
    "create_app", "current_priority", "current_tenant",
    "deadline_scope", "make_router_app", "priority_scope",
    "tenant_scope", "token_cap_scope", "validate_phase",
    "validate_priority", "validate_tenant",
]
