"""Preemptive, priority-aware scheduling on the paged KV pool.

ROADMAP item 3: before this module, pool exhaustion PARKED admissions
in a single FIFO slot and the flight recorder merely *named* a
``preempt_candidate`` without acting on it — one bulk tenant could
stall every other caller behind a full pool. This module makes the
engine act under pressure instead of queueing (vLLM/PagedAttention
preemption-by-eviction + Sarathi-Serve stall-free mixing lineage):

- **Priority classes** (:data:`PRIORITIES` — ``high`` / ``normal`` /
  ``low``): every request carries one, set by the ``X-Priority``
  header on all three transports (validated like ``X-Tenant-ID`` —
  closed value set, 422 on garbage, echoed on responses, carried by
  :class:`~unionml_tpu.serving.router.HttpReplica` across the router
  hop) or the ``priority=`` argument of
  :meth:`~unionml_tpu.serving.engine.DecodeEngine.generate`.
- **A real waiting room** (:class:`WaitingRoom`) replacing the
  engine's single internal FIFO + one-request park slot: per-priority,
  per-tenant queues drained by **deficit-weighted round robin**.
  Classes share admission throughput by :attr:`SchedulerConfig
  .class_weights` under stride scheduling (smallest virtual pass
  serves, advancing by cost/weight), so a backlogged ``low`` class is
  starvation-BOUNDED, not starved: it receives exactly
  ``w_low / Σw`` of admitted token throughput — docs/robustness.md
  "Preemption & fairness" derives the bound. Within a class, tenants
  take turns under DRR where each tenant's refill quantum is scaled by
  its :meth:`~unionml_tpu.serving.usage.UsageLedger.fair_share` — a
  tenant that already consumed most of the device gets a smaller
  quantum, so a bulk tenant cannot crowd out its class's light users.
- **Preemption policy** (:meth:`PreemptiveScheduler.select_victim`):
  when a reservation parks on pool exhaustion and a strictly
  lower-priority resident exists, the engine evicts that victim's KV
  blocks to the host prefix-cache block store (pool and cache share
  one block unit — eviction is the existing extract path, resume the
  existing splice path: pointer swaps, not recompute) and re-admits it
  later with exact token parity. Victims: lowest priority class
  first, most recently admitted within the class (LIFO — the
  longest-running streams, closest to completion, are spared), and
  only streams whose resume prompt still fits an admission bucket.
- **Stall-free mixing**: :attr:`SchedulerConfig.mix_prefill_tokens`
  is the Sarathi-style token budget of lead prefill-chunk work the
  dispatcher interleaves into each pass between decode chunks, so a
  long prompt admits faster without stalling the decode lane (chunked
  prefill already existed; this is the knob the scheduler never had).

Telemetry: ``unionml_preemptions_total{engine,cause}`` counts evictions
by cause, ``unionml_sched_waiting_depth{engine,priority}`` gauges the
waiting room per class, and the flight recorder gains ``preempt`` /
``resume`` / ``promote`` lifecycle events (docs/observability.md).

Thread-safety: :class:`WaitingRoom` has its own lock (submitters,
the dispatcher, and the harvester's resume requeue all touch it);
:class:`PreemptiveScheduler` is a thin facade the engine drives.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Mapping, Optional, Tuple

from unionml_tpu import telemetry

__all__ = [
    "DEFAULT_MODEL_VERSION",
    "DEFAULT_PHASE",
    "DEFAULT_PRIORITY",
    "PHASES",
    "PRIORITIES",
    "PreemptiveScheduler",
    "SchedulerConfig",
    "WaitingRoom",
    "current_model_version",
    "current_priority",
    "current_token_cap",
    "model_version_scope",
    "priority_scope",
    "token_cap_scope",
    "validate_model_version",
    "validate_phase",
    "validate_priority",
    "validate_token_cap",
]

# CLOSED value set (metric-label-safe, like usage.DROP_CAUSES): the
# transports validate against it so a hostile X-Priority can never
# reach the scheduler as an unknown class
PRIORITIES = ("high", "normal", "low")
DEFAULT_PRIORITY = "normal"
_RANK = {p: i for i, p in enumerate(PRIORITIES)}  # 0 = most urgent

# preemption causes are a closed set too (the
# unionml_preemptions_total{cause} label): "priority" = a
# higher-priority waiter displaced a lower-priority resident
PREEMPT_CAUSES = ("priority",)

# serving PHASES (docs/serving.md "Disaggregated serving"): which half
# of a generative request an engine pool owns. ``colocated`` (the
# default) serves both — the historical single-pool architecture; a
# phase-split fleet runs ``prefill`` engines (prompt prefill + KV
# export, DistServe/Splitwise lineage) and ``decode`` engines (KV
# splice + token streaming) behind one phase-aware router. A CLOSED
# set like PRIORITIES: phase rides metric labels, flight-event tags,
# and the fleet dashboard, so the value space must stay enumerable.
PHASES = ("prefill", "decode", "colocated")
DEFAULT_PHASE = "colocated"


def validate_phase(value: Optional[str]) -> str:
    """Normalize an engine/replica ``phase``: ``None``/empty →
    :data:`DEFAULT_PHASE`; anything outside :data:`PHASES` raises
    ``ValueError`` — the set is closed (label- and dashboard-safe)."""
    if value is None or value == "":
        return DEFAULT_PHASE
    phase = str(value).lower()
    if phase not in PHASES:
        raise ValueError(
            f"unknown serving phase {value!r}: must be one of "
            f"{'/'.join(PHASES)}"
        )
    return phase


def validate_priority(value: Optional[str]) -> str:
    """Normalize an ``X-Priority`` header / ``priority=`` argument:
    ``None``/empty → :data:`DEFAULT_PRIORITY`; anything outside
    :data:`PRIORITIES` (case-insensitive) raises ``ValueError`` (the
    transports map it to 422) — mirroring
    :func:`~unionml_tpu.serving.usage.validate_tenant`: a hostile
    header is rejected at the boundary, never minted into scheduler
    state or a label value."""
    if value is None or value == "":
        return DEFAULT_PRIORITY
    priority = str(value).lower()
    if priority not in PRIORITIES:
        raise ValueError(
            f"unknown priority {value!r}: X-Priority must be one of "
            f"{'/'.join(PRIORITIES)}"
        )
    return priority


def priority_rank(priority: str) -> int:
    """Class rank, 0 = most urgent (validated input assumed)."""
    return _RANK[priority]


_priority_tls = threading.local()


@contextmanager
def priority_scope(priority: Optional[str]) -> Iterator[None]:
    """Expose ``priority`` to engine submissions on this thread
    (``None`` leaves any outer scope visible) — the same thread-local
    plumbing as :func:`~unionml_tpu.serving.usage.tenant_scope`; the
    transports open it around the predictor call from ``X-Priority``."""
    if priority is None:
        yield
        return
    prev = getattr(_priority_tls, "priority", None)
    _priority_tls.priority = priority
    try:
        yield
    finally:
        _priority_tls.priority = prev


def current_priority() -> str:
    """The innermost :func:`priority_scope` value on this thread, else
    :data:`DEFAULT_PRIORITY`."""
    priority = getattr(_priority_tls, "priority", None)
    return priority if priority else DEFAULT_PRIORITY


# model-version request pinning (docs/robustness.md "Rollouts &
# rollback"): the ``X-Model-Version`` header vocabulary. Unlike
# PRIORITIES the value space is registry versions, not a static enum,
# so the boundary validates a closed GRAMMAR (label-safe slug, bounded
# length) and the router's version-aware pick rejects ids that name no
# registered version. ``auto`` is the no-pin sentinel: the request
# follows the fleet's live/canary split.
DEFAULT_MODEL_VERSION = "auto"
MAX_MODEL_VERSION_LEN = 64
_MODEL_VERSION_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyz0123456789._-"
)


def validate_model_version(value: Optional[str]) -> str:
    """Normalize an ``X-Model-Version`` header: ``None``/empty →
    :data:`DEFAULT_MODEL_VERSION` (no pin); anything else must be a
    label-safe slug — lowercase alphanumerics plus ``._-``, leading
    alphanumeric, at most :data:`MAX_MODEL_VERSION_LEN` chars — or
    ``ValueError`` (→ 422). Grammar-closed like
    :func:`~unionml_tpu.serving.usage.validate_tenant`: a hostile
    header is rejected at the boundary, never minted into a metric
    label or flight-event field; whether the id names a *registered*
    version is the router pick's check, because only the fleet knows
    its registry."""
    if value is None or value == "":
        return DEFAULT_MODEL_VERSION
    version = str(value).lower()
    if len(version) > MAX_MODEL_VERSION_LEN:
        raise ValueError(
            f"model version too long ({len(version)} chars, max "
            f"{MAX_MODEL_VERSION_LEN})"
        )
    if not version[0].isalnum() or not all(
        c in _MODEL_VERSION_OK for c in version
    ):
        raise ValueError(
            f"invalid model version {value!r}: X-Model-Version must be "
            "a slug of [a-z0-9._-] starting alphanumeric"
        )
    return version


_model_version_tls = threading.local()


@contextmanager
def model_version_scope(version: Optional[str]) -> Iterator[None]:
    """Expose a validated ``X-Model-Version`` pin to the router on
    this thread (``None`` leaves any outer scope visible) — the
    :func:`priority_scope` plumbing applied to version pinning: the
    transports open it around the predictor call, and
    :class:`~unionml_tpu.serving.router.HttpReplica` re-emits it
    across the router hop so a pinned request stays pinned through a
    router-of-routers."""
    if version is None:
        yield
        return
    prev = getattr(_model_version_tls, "version", None)
    _model_version_tls.version = version
    try:
        yield
    finally:
        _model_version_tls.version = prev


def current_model_version() -> str:
    """The innermost :func:`model_version_scope` value on this thread,
    else :data:`DEFAULT_MODEL_VERSION` (no pin)."""
    version = getattr(_model_version_tls, "version", None)
    return version if version else DEFAULT_MODEL_VERSION


def validate_token_cap(value) -> Optional[int]:
    """Normalize a per-request ``max_new_tokens`` cap from a payload
    field: ``None`` → no cap (the engine default applies); anything
    else must be an integer ``>= 1`` or ``ValueError`` (→ 422) — the
    cap crosses the router hop in the ``/predict`` payload, so a
    hostile body must be rejected at the boundary like a hostile
    header."""
    if value is None:
        return None
    if isinstance(value, bool) or (
        not isinstance(value, int) and not (
            isinstance(value, float) and value.is_integer()
        )
    ):
        raise ValueError(
            f"max_new_tokens must be an integer >= 1, got {value!r}"
        )
    cap = int(value)
    if cap < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {cap}")
    return cap


_token_cap_tls = threading.local()


@contextmanager
def token_cap_scope(cap: Optional[int]) -> Iterator[None]:
    """Expose a per-request ``max_new_tokens`` cap to engine
    submissions on this thread (``None`` leaves any outer scope
    visible) — the deadline-scope plumbing applied to the token cap:
    the transports open it from the ``/predict`` payload's
    ``max_new_tokens`` field, so an engine-backed predictor honors the
    caller's cap without threading a kwarg through every wrapper (and
    the cap survives the router hop — disaggregated two-leg dispatch
    needs it for token parity)."""
    if cap is None:
        yield
        return
    prev = getattr(_token_cap_tls, "cap", None)
    _token_cap_tls.cap = int(cap)
    try:
        yield
    finally:
        _token_cap_tls.cap = prev


def current_token_cap() -> Optional[int]:
    """The innermost :func:`token_cap_scope` value on this thread, or
    ``None`` (no per-request cap — the engine default applies)."""
    return getattr(_token_cap_tls, "cap", None)


@dataclass(frozen=True)
class SchedulerConfig:
    """Knobs for :class:`PreemptiveScheduler` / :class:`WaitingRoom`.

    Args:
        class_weights: admission-throughput shares per priority class
            (stride scheduling: the class with the smallest virtual
            pass serves and advances by ``cost / weight``), so under
            full backlog class ``c`` receives ``w_c / Σw`` of admitted
            token throughput — the starvation bound (``low`` is
            slowed, never stopped). Keys must cover
            :data:`PRIORITIES` exactly.
        quantum_tokens: DRR refill per tenant visit, in prompt+decode
            tokens; scaled by the tenant's ledger fair share. Smaller
            quanta interleave tenants finer at more rotation cost.
        min_fair_weight: floor on the usage-fed tenant weight, so a
            tenant that consumed ~100% of the device still drains
            (slowly) instead of deadlocking its queue.
        preempt: ``True`` forces preemption on (raises at engine
            construction when the prerequisites — paged pool + prefix
            cache — are missing), ``False`` disables it (park-only, the
            pre-scheduler behavior), ``None`` (default) auto-enables
            exactly when the engine can evict-and-resume losslessly.
        mix_prefill_tokens: Sarathi-style stall-free mixing budget —
            lead prefill-chunk tokens the dispatcher interleaves into
            ONE pass between decode chunks. ``None`` (default) keeps
            the historical one-admission-step-per-pass cadence;
            a larger budget admits long prompts faster at the cost of
            more prefill compute between decode chunks.
    """

    class_weights: Mapping[str, int] = field(
        default_factory=lambda: {"high": 16, "normal": 4, "low": 1}
    )
    quantum_tokens: int = 256
    min_fair_weight: float = 0.05
    preempt: Optional[bool] = None
    mix_prefill_tokens: Optional[int] = None

    def __post_init__(self):
        if set(self.class_weights) != set(PRIORITIES):
            raise ValueError(
                f"class_weights must cover exactly {PRIORITIES}, got "
                f"{tuple(self.class_weights)}"
            )
        if any(w < 1 for w in self.class_weights.values()):
            raise ValueError("class_weights must all be >= 1")
        if self.quantum_tokens < 1:
            raise ValueError("quantum_tokens must be >= 1")
        if not 0.0 < self.min_fair_weight <= 1.0:
            raise ValueError("min_fair_weight must be in (0, 1]")
        if self.mix_prefill_tokens is not None and self.mix_prefill_tokens < 1:
            raise ValueError("mix_prefill_tokens must be >= 1 when set")


class WaitingRoom:
    """Priority/tenant waiting room: the engine's admission queue.

    Replaces the engine's internal FIFO ``queue.Queue`` + single-slot
    park: requests wait in per-(priority, tenant) deques, drained by
    weighted-class + per-tenant-DRR :meth:`pop`; pool-exhausted
    admissions :meth:`park` into a bounded parked lane (at most one
    entry per priority class — a parked request blocks its own class
    and every class below it, preserving the old FIFO-under-pressure
    contract, while strictly higher classes may still admit past it:
    the ``promote`` path).

    Requests need only ``.priority``, ``.tenant``, ``.prompt`` and
    ``.max_new_tokens`` attributes (the engine's ``_Request``). All
    methods are thread-safe; the engine calls some under its own lock,
    so nothing here calls back into engine state.
    """

    def __init__(
        self,
        config: Optional[SchedulerConfig] = None,
        *,
        usage=None,
        on_depth=None,
    ):
        self.config = config if config is not None else SchedulerConfig()
        self._usage = usage
        self._on_depth = on_depth  # callback(priority, depth) → gauges
        self._lock = threading.Lock()
        # priority → tenant → deque of requests (OrderedDict preserves
        # the DRR rotation order; rotation moves served tenants back)
        self._queues: Dict[str, "OrderedDict[str, deque]"] = {
            p: OrderedDict() for p in PRIORITIES
        }
        self._depths: Dict[str, int] = {p: 0 for p in PRIORITIES}
        # stride-scheduling state across classes: each class carries a
        # virtual "pass"; the eligible class with the smallest pass
        # serves and advances by cost/weight, so admitted-token shares
        # converge EXACTLY to class_weights. _vtime is the pass of the
        # last served class — a class going from empty to backlogged
        # joins at it, so idle periods bank no credit.
        self._class_pass: Dict[str, float] = {p: 0.0 for p in PRIORITIES}
        self._vtime = 0.0
        # per-tenant DRR deficits within each class
        self._deficit: Dict[str, Dict[str, float]] = {
            p: {} for p in PRIORITIES
        }
        # parked lane: pool-exhausted admissions awaiting blocks, at
        # most one per class (strictly-higher classes admit past them)
        self._parked: List[Any] = []

    # ------------------------------------------------------------------ #
    # depth views
    # ------------------------------------------------------------------ #

    def qsize(self) -> int:
        """Queued (not yet popped) requests — the ``max_queue_depth``
        bound's denominator, matching the old FIFO's accounting (parked
        requests were already popped and are counted by the engine's
        ``_admitting``)."""
        with self._lock:
            return sum(self._depths.values())

    def empty(self) -> bool:
        return self.qsize() == 0

    def parked_count(self) -> int:
        with self._lock:
            return len(self._parked)

    def depths(self) -> Dict[str, int]:
        """Per-class queued depth (the waiting-depth gauge view)."""
        with self._lock:
            return dict(self._depths)

    def _publish_locked(self, priority: str) -> None:
        if self._on_depth is not None:
            self._on_depth(priority, self._depths[priority])

    # ------------------------------------------------------------------ #
    # enqueue / dequeue
    # ------------------------------------------------------------------ #

    @staticmethod
    def _cost(req) -> int:
        """A request's admission cost in tokens (prompt + worst-case
        decode) — the unit both DRR layers account in."""
        return len(req.prompt) + int(req.max_new_tokens)

    def put(self, req, *, front: bool = False) -> None:
        """Enqueue ``req`` under its (priority, tenant). ``front=True``
        places it at the head of its queue — the resume path, so a
        preempted stream re-admits before its tenant's fresh arrivals."""
        with self._lock:
            if self._depths[req.priority] == 0:
                # the class joins the stride schedule at the current
                # virtual time: an idle class must not have banked a
                # tiny pass it could monopolize admissions with
                self._class_pass[req.priority] = max(
                    self._class_pass[req.priority], self._vtime
                )
            tenants = self._queues[req.priority]
            q = tenants.get(req.tenant)
            if q is None:
                q = deque()
                tenants[req.tenant] = q
                self._deficit[req.priority].setdefault(req.tenant, 0.0)
            if front:
                q.appendleft(req)
            else:
                q.append(req)
            self._depths[req.priority] += 1
            self._publish_locked(req.priority)

    def _fair_weight(self, tenant: str) -> float:
        """Usage-fed DRR weight: 1 − the tenant's attributed share of
        device time, floored at ``min_fair_weight`` — heavy tenants
        refill slower, light ones catch up (VTC-style fairness on the
        ledger PR 8 built)."""
        if self._usage is None:
            return 1.0
        share = self._usage.fair_share(tenant)
        return max(self.config.min_fair_weight, 1.0 - share)

    def _pop_class_locked(self, priority: str):
        """Per-tenant DRR within one class: rotate tenants, refilling
        each visited tenant's deficit by ``quantum × fair_weight``,
        and serve the first head whose deficit covers its cost. The
        rotation always terminates: deficits grow every visit."""
        tenants = self._queues[priority]
        deficits = self._deficit[priority]
        quantum = self.config.quantum_tokens
        # prune empty tenant queues first so the rotation is over live
        # work only; a pruned tenant's deficit resets (classic DRR —
        # an idle tenant must not bank credit for a later burst)
        for t in [t for t, q in tenants.items() if not q]:
            del tenants[t]
            deficits.pop(t, None)
        if not tenants:
            return None
        while True:
            for tenant in list(tenants):
                q = tenants[tenant]
                deficits[tenant] = (
                    deficits.get(tenant, 0.0)
                    + quantum * self._fair_weight(tenant)
                )
                head = q[0]
                cost = self._cost(head)
                if deficits[tenant] >= cost:
                    deficits[tenant] -= cost
                    q.popleft()
                    tenants.move_to_end(tenant)  # round-robin rotation
                    if not q:
                        del tenants[tenant]
                        deficits.pop(tenant, None)
                    self._depths[priority] -= 1
                    self._publish_locked(priority)
                    return head
                tenants.move_to_end(tenant)

    def pop(self, *, above_rank: Optional[int] = None):
        """Dequeue the next admission candidate, or ``None``.

        Class selection is STRIDE SCHEDULING (a deterministic lottery):
        the eligible class with the smallest virtual pass serves, then
        advances its pass by ``cost / weight`` — so admitted-token
        shares converge exactly to :attr:`SchedulerConfig
        .class_weights` under contention. That IS the starvation
        bound: a backlogged class with weight ``w`` receives at least
        ``w / Σ weights`` of admitted token throughput, never zero
        (docs/robustness.md derives the per-request wait bound). While
        anything is parked, only classes STRICTLY more urgent than the
        most-urgent parked request are eligible (the parked head
        blocks its class and below — FIFO-under-pressure is preserved;
        a pop that jumps a parked head is the ``promote`` event the
        engine records). ``above_rank`` narrows eligibility further
        (ranks strictly below it, i.e. more urgent)."""
        with self._lock:
            limit = above_rank
            if self._parked:
                parked_rank = min(
                    priority_rank(r.priority) for r in self._parked
                )
                limit = (
                    parked_rank if limit is None else min(limit, parked_rank)
                )
            eligible = [
                p for p in PRIORITIES
                if self._depths[p] > 0
                and (limit is None or priority_rank(p) < limit)
            ]
            if not eligible:
                return None
            # smallest pass serves; PRIORITIES order breaks ties
            # toward the more urgent class
            best = min(
                eligible,
                key=lambda p: (self._class_pass[p], priority_rank(p)),
            )
            req = self._pop_class_locked(best)
            if req is not None:
                self._vtime = self._class_pass[best]
                self._class_pass[best] += (
                    self._cost(req) / self.config.class_weights[best]
                )
            return req

    # ------------------------------------------------------------------ #
    # parked lane (pool-exhausted admissions)
    # ------------------------------------------------------------------ #

    def park(self, req) -> None:
        """Move a pool-exhausted admission into the parked lane (the
        engine retries it every dispatcher pass via
        :meth:`take_parked`). Bounded by construction: at most one
        parked request per priority class, because :meth:`pop` only
        releases candidates from classes strictly above every parked
        entry."""
        with self._lock:
            if req not in self._parked:
                self._parked.append(req)
                # most-urgent first, FIFO within a class (stable sort)
                self._parked.sort(key=lambda r: priority_rank(r.priority))

    def take_parked(self):
        """The parked request to retry this pass (most urgent first),
        removed from the lane — the engine re-:meth:`park`\\ s it if
        its reservation still fails."""
        with self._lock:
            if not self._parked:
                return None
            return self._parked.pop(0)

    def is_parked(self, req) -> bool:
        with self._lock:
            return req in self._parked

    def pop_all(self) -> List[Any]:
        """Drain everything — queued AND parked — for engine close."""
        with self._lock:
            out: List[Any] = list(self._parked)
            self._parked = []
            for p in PRIORITIES:
                for q in self._queues[p].values():
                    out.extend(q)
                self._queues[p].clear()
                self._depths[p] = 0
                self._publish_locked(p)
            return out


class PreemptiveScheduler:
    """The engine-facing facade: waiting room + victim policy + the
    scheduler's own telemetry series. One per engine instance."""

    def __init__(
        self,
        config: Optional[SchedulerConfig] = None,
        *,
        registry: Optional[telemetry.MetricsRegistry] = None,
        engine_label: str = "engine-0",
        usage=None,
        phase: Optional[str] = None,
    ):
        self.config = config if config is not None else SchedulerConfig()
        # the owning engine's serving phase (prefill/decode/colocated):
        # rides stats() so a phase-split fleet's per-engine scheduler
        # views are attributable to their pool
        self.phase = validate_phase(phase)
        self._registry = (
            registry if registry is not None else telemetry.get_registry()
        )
        self.engine_label = engine_label
        depth_gauge = self._registry.gauge(
            "unionml_sched_waiting_depth",
            "Waiting-room depth per priority class (requests queued "
            "awaiting admission, parked pool-exhausted admissions "
            "excluded).",
            ("engine", "priority"),
        )
        self._g_depth = {
            p: depth_gauge.labels(engine=engine_label, priority=p)
            for p in PRIORITIES
        }
        preempted = self._registry.counter(
            "unionml_preemptions_total",
            "Resident streams evicted to the host prefix-cache block "
            "store by the preemptive scheduler, by cause (priority = a "
            "higher-priority waiter displaced a lower-priority "
            "resident); every preemption is later resumed via the "
            "splice path with exact token parity.",
            ("engine", "cause"),
        )
        self._m_preempted = {
            cause: preempted.labels(engine=engine_label, cause=cause)
            for cause in PREEMPT_CAUSES
        }
        self.room = WaitingRoom(
            self.config, usage=usage,
            on_depth=lambda p, d: self._g_depth[p].set(d),
        )

    # ------------------------------------------------------------------ #
    # preemption policy
    # ------------------------------------------------------------------ #

    def select_victim(
        self, waiter, residents: List[Tuple[int, Any]]
    ) -> Optional[Tuple[int, Any]]:
        """Pick the resident to evict for ``waiter``, or ``None``.

        Policy (docs/robustness.md "Preemption & fairness"): only
        residents in a STRICTLY lower priority class than the waiter
        are candidates (equal-priority contention parks FIFO, so a
        class can never thrash itself); among candidates, the lowest
        class loses first, ties broken by the most recent admission
        (LIFO — the longest-running streams, closest to completion
        and holding the most reusable KV, are spared). ``residents``
        is the engine's pre-filtered ``(slot, request)`` eligibility
        list (prefill harvested, not abandoned, resume prompt fits a
        bucket)."""
        waiter_rank = priority_rank(waiter.priority)
        candidates = [
            (slot, r) for slot, r in residents
            if priority_rank(r.priority) > waiter_rank
        ]
        if not candidates:
            return None
        return max(
            candidates,
            key=lambda sr: (priority_rank(sr[1].priority), sr[1].submitted),
        )

    def record_preemption(self, cause: str = "priority") -> None:
        if cause not in PREEMPT_CAUSES:  # closed label set
            cause = PREEMPT_CAUSES[0]
        self._m_preempted[cause].inc()

    # ------------------------------------------------------------------ #
    # views
    # ------------------------------------------------------------------ #

    def preemptions(self) -> int:
        return int(sum(c.value for c in self._m_preempted.values()))

    def stats(self) -> dict:
        """The ``scheduler`` section of ``DecodeEngine.stats()``."""
        return {
            "phase": self.phase,
            "waiting": self.room.depths(),
            "parked": self.room.parked_count(),
            "preemptions": self.preemptions(),
            "class_weights": dict(self.config.class_weights),
            "quantum_tokens": self.config.quantum_tokens,
        }

    def reset_stats(self) -> None:
        for c in self._m_preempted.values():
            c.reset()
