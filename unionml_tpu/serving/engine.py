"""Continuous-batching decode engine: step-boundary request joins.

Supersedes the reference's one-predictor-call-per-request loop
(reference: unionml/fastapi.py:50-64) *and* this package's own
full-batch micro-batcher for LLM serving: the MicroBatcher drains the
queue, runs one ``generate()`` to completion, and only then admits the
next batch — a request arriving one step after a batch launches waits
the entire in-flight decode plus its own (measured on Llama-3-8B int8,
one v5e chip: 8-client p95 = 1040 ms vs p50 = 498 ms, BASELINE.md).

This engine holds a **fixed-slot decode batch** resident on device:

- the KV cache is ``[slots, L, kv_heads, head_dim]`` per layer with a
  per-slot fill index (vector ``cache_index`` — see
  :class:`unionml_tpu.models.layers.Attention`);
- a new request's prompt is **prefilled into a free slot** between
  decode steps (its own small ``[1, bucket]`` program, then one
  ``dynamic_update_slice`` of the produced KV rows into the slot);
  buckets larger than ``prefill_chunk`` admit **chunked**: the lead
  chunks fill a standalone fresh cache one ``[1, chunk]`` program at a
  time with decode chunks interleaved between them, so resident slots
  keep streaming tokens while an 8k-class prompt admits instead of
  head-of-line-blocking behind its whole prefill (the long-context
  serving path; only ``ceil(true_len / chunk)`` chunk programs run, so
  a short prompt in a long bucket pays for its own length);
- decode runs in **chunks of ``chunk_steps`` inside one
  ``lax.scan``**, and up to ``pipeline_depth`` chunks are **dispatched
  asynchronously** — the dispatcher thread never blocks on a chunk's
  tokens before enqueueing the next; a separate HARVESTER thread blocks
  on the oldest in-flight readback and accounts its tokens.
  (``is_ready()`` polling was measured and rejected: it serializes the
  tunneled command stream — 226 ms/chunk vs 26.7 ms pure compute,
  BASELINE.md round 3 — so the engine blocks in a dedicated thread
  instead.) Device-side state donation chains the chunks in dispatch
  order, so correctness never depends on host timing. This matters
  enormously when the host↔device round trip is slow (measured here:
  ~119 ms through the tunneled backend vs ~2 ms of actual decode
  compute per step — a blocking per-chunk loop would be ~5x slower than
  one monolithic generate);
- finished slots (eos / token budget) are retired when their tokens are
  harvested and immediately reusable; a per-slot **generation counter**
  keeps tokens from an in-flight chunk dispatched for the *previous*
  occupant from leaking into the new one. Device-side ``done``/
  ``active`` masking keeps retired slots from corrupting live cache
  rows, and ``(pipeline_depth + 1) * chunk_steps`` spare cache rows
  absorb the decode overshoot between a request's completion and the
  host noticing it.

TPU-first notes: every program has static shapes (slots, bucket set,
chunk length are fixed at construction — XLA compiles
``len(prompt_buckets) + 1`` executables total, plus three per chunked
bucket: fresh-init, lead chunk, final chunk); the per-slot cache write
is a vmapped ``dynamic_update_slice`` (one scatter); state is donated
through both programs so the multi-GB cache never copies.

Prompts are placed **unpadded** at cache rows ``[0, P)`` — per-slot
positions make left-padding unnecessary, so a slot-decoded sequence is
token-identical to its solo :func:`~unionml_tpu.models.generate
.make_generator` run (tested in tests/unit/test_engine.py).

Automatic prefix reuse: built with a
:class:`~unionml_tpu.serving.prefix_cache.RadixPrefixCache`, admission walks
a radix tree of previously-served prompt prefixes, splices the matched
KV block rows host→device into the fresh cache (one compiled
``[1, block]`` splice program, dispatched through the same interleaved
admission loop as chunked prefill), and prefills only the uncovered
suffix; prefill completion extracts the prompt's new full blocks
device→host (async copy) and inserts them back into the tree. A shared
``system_prefix`` is a back-compat shim over this path: its tokens are
prepended to every request and its blocks are pinned in the cache, so
it is prefilled once and never evicted (docs/prefix_caching.md).

Fault tolerance (docs/robustness.md): submissions pass **admission
control** — a bounded queue (``max_queue_depth`` →
:class:`~unionml_tpu.serving.faults.Overloaded`), per-request deadlines
(``deadline_ms``, or an ambient :func:`~unionml_tpu.serving.faults
.deadline_scope`) shed at dequeue before they consume prefill, and a
**circuit breaker** that rejects fast while the engine is repeatedly
failing to rebuild. A failed device program no longer kills every
in-flight request: :meth:`_recover` fails only the poisoned batch (the
resident occupants + the in-progress admission, whose donated device
state the error invalidated), rebuilds decode state, and lets queued
survivors re-admit; in-flight readbacks from the poisoned era are
epoch-tagged and never materialized. :meth:`drain` stops admissions and
finishes in-flight streams for graceful shutdown/redeploy, and a
:class:`~unionml_tpu.serving.faults.FaultInjector` provides the
deterministic injection points that make all of the above CPU-testable.
"""

from __future__ import annotations

import math
import queue
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence

import numpy as np

from unionml_tpu import telemetry
from unionml_tpu._logging import logger
from unionml_tpu.serving.faults import (
    DeadlineExceeded,
    EngineUnavailable,
    Overloaded,
    current_deadline_ms,
)
from unionml_tpu.serving.kv_pool import KVBlockPool, PoolExhausted
from unionml_tpu.serving.scheduler import (
    DEFAULT_PRIORITY,
    PRIORITIES,
    PreemptiveScheduler,
    SchedulerConfig,
    current_priority,
    current_token_cap,
    priority_rank,
    validate_phase,
    validate_priority,
)
from unionml_tpu.serving.usage import (
    DEFAULT_TENANT,
    current_tenant,
    validate_tenant,
)

__all__ = ["DecodeEngine"]


def _start_host_copy(arr) -> None:
    """Kick off the device→host transfer early so the later harvest's
    ``np.asarray`` finds the bytes already local."""
    try:
        arr.copy_to_host_async()
    except AttributeError:
        pass


def _splice_rows(dst_tree, src_tree, b_start, r_start):
    """Write ``src_tree``'s rows into ``dst_tree`` at (batch, row) offset
    ``(b_start, r_start)`` — per layer, per buffer, rank-generic (covers
    the bf16 [B, L, H, D] KV buffers and the int8-cache [B, L, H] scale
    planes alike). The single home for the engine's three cache splices
    (prefix seed broadcast, per-request fresh-cache seed, suffix
    placement)."""
    import jax

    return tuple(
        tuple(
            jax.lax.dynamic_update_slice(
                dst, src.astype(dst.dtype),
                (b_start, r_start) + (0,) * (dst.ndim - 2),
            )
            for dst, src in zip(dst_layer, src_layer)
        )
        for dst_layer, src_layer in zip(dst_tree, src_tree)
    )


def _host_blocks(full, j0: int, j1: int):
    """Owned ``[1, block, ...]`` host copies of blocks ``[j0, j1)``
    from a block-major extract ([n_blocks, block, ...] per buffer —
    the table-addressed gather): block j is row j, re-leading-axised
    to the prefix cache's store form. The SINGLE home for the
    re-axis (the harvest-insert and preempt-save paths both feed the
    same store — a layout change applied to one and not the other
    would silently corrupt resumes or cache hits); ``.copy()`` so a
    stored block never pins the whole extract window in RAM."""
    return [
        tuple(
            tuple(buf[j][None].copy() for buf in layer)
            for layer in full
        )
        for j in range(j0, j1)
    ]


def _concat_rows(trees):
    """Concatenate host KV block trees along the row axis (axis 1) —
    groups cache blocks into one splice-unit tree host-side, so the
    device splice count scales with the admission's chunk unit, not the
    cache's block size."""
    return tuple(
        tuple(np.concatenate(bufs, axis=1) for bufs in zip(*layers))
        for layers in zip(*trees)
    )


@dataclass
class _Admission:
    """A chunked prefill in progress: host cursor over the lead chunks.

    The fresh cache lives here (device-side), not in the engine state —
    lead chunk dispatches donate it forward while decode chunks donate
    the resident state, so the two program streams never contend for a
    buffer and interleave freely in dispatch order."""

    req: "_Request"
    slot: int
    bucket: int
    chunk: int                      # tokens per program (prefill_chunk,
    #                                 or the prefix-cache block size)
    n_chunks: int                   # total programs incl. the final
    padded: np.ndarray              # [bucket] right-padded prompt
    fresh: Any                      # [1, bucket] cache being filled
    # paged mode: the slot's pool block ids for the final scatter
    # ([bucket/block] int32; uncovered tail entries = trash block)
    pool_ids: Optional[np.ndarray] = None
    next_chunk: int = 0
    # prefix-cache hit: one entry per chunk-sized splice unit (a tuple
    # of cached host block trees covering rows [i*chunk, (i+1)*chunk)),
    # spliced before the remaining chunks run (next_chunk starts past
    # them)
    splice_rows: List[Any] = field(default_factory=list)
    next_splice: int = 0


@dataclass(eq=False)  # identity semantics: the waiting room's parked
# lane membership tests (`req in parked`) must never field-compare two
# requests — the numpy prompt would make `==` ambiguous
class _Request:
    prompt: np.ndarray                  # int32 [P], truncated to max bucket
    max_new_tokens: int
    submitted: float = field(default_factory=time.perf_counter)
    tokens: List[int] = field(default_factory=list)
    event: threading.Event = field(default_factory=threading.Event)
    error: Optional[BaseException] = None
    # streaming consumers: harvested token chunks are mirrored here as
    # they land (lists of ints; None terminates; the terminal push
    # follows error/event so a drained stream is a finished request)
    stream: Optional["queue.Queue"] = None
    # observability (ms). prefill_ms and decode_ms are measured at token
    # HARVEST, so each includes one in-flight readback lag — honest at
    # the request boundary, not a pure device timing. ttft_ms is
    # submit→first-harvested-token: the latency a streaming client sees
    # to its first event.
    queue_wait_ms: float = 0.0
    prefill_ms: float = 0.0
    decode_ms: float = 0.0
    ttft_ms: float = 0.0
    abandoned: bool = False             # waiter gave up (timeout): retire asap
    rid: str = ""                       # telemetry trace-span request id
    # usage metering (docs/observability.md "Usage metering"): the
    # validated tenant id this request's resource vector is billed to
    tenant: str = DEFAULT_TENANT
    # preemptive scheduling (docs/robustness.md "Preemption &
    # fairness"): the validated priority class (X-Priority header /
    # generate(priority=)); the waiting room orders admissions by it
    # and the scheduler may evict strictly-lower-priority residents
    priority: str = DEFAULT_PRIORITY
    # absolute perf_counter deadline (None = none): checked at DEQUEUE,
    # so an expired request is shed before it consumes prefill
    deadline: Optional[float] = None
    _prefill_end: float = 0.0
    _dispatch_t: float = 0.0
    _expected: int = 0                  # tokens covered by dispatched work
    _chunk_i: int = 0                   # harvested decode chunks (trace names)
    _lease: Optional[Any] = None        # PrefixLease pinning matched blocks
    _matched_blocks: int = 0            # radix-tree blocks found at admission
    _prefilled_tokens: int = 0          # prompt tokens actually prefilled
    _saved_tokens: int = 0              # prompt tokens spliced from cache
    # paged mode: device pool bookkeeping (engine lock guards all three)
    _block_ids: List[int] = field(default_factory=list)  # taken pool blocks
    _resv_blocks: int = 0               # reserved, not yet taken
    _rows_cap: int = 0                  # prompt + max_new (block budget)
    _park_logged: bool = False          # one pool_pressure event per park
    _pool_gen: int = 0                  # pool generation at reservation
    # usage metering: pool-block take timestamps (parallel to
    # _block_ids' take order) and dispatched-prefill FLOPs accumulated
    # from the tracker's per-program cost analysis
    _block_t0: List[float] = field(default_factory=list)
    _attr_flops: float = 0.0
    # preemption bookkeeping: times evicted, when the last eviction
    # happened (resume-wait span anchor; also marks the request as
    # resumed so ttft/queue timings are not overwritten), and the
    # lease pinning the evicted KV blocks in the host prefix cache
    # until the resume admission takes its own
    _preempts: int = 0
    _preempted_at: float = 0.0
    _resume_lease: Optional[Any] = None
    # generated tokens already FOLDED INTO ``prompt`` by a previous
    # resume: the next eviction appends only tokens[_prompt_incl:], or
    # a twice-preempted stream would duplicate its first segment
    _prompt_incl: int = 0
    # disaggregated prefill (docs/serving.md "Disaggregated serving"):
    # set by prefill_export and signalled once the request's KV blocks
    # have landed in the host prefix-cache store (the insert entry's
    # lease release — or any terminal path, so a waiter never hangs)
    _kv_event: Optional[threading.Event] = None
    # serving goodput plane (docs/observability.md "Serving goodput &
    # tail attribution"): admission_ms is the host-side admission span
    # (dispatch start → final prefill program dispatched — the
    # chunked-admission machinery's share of prefill_ms); _itl_anchor
    # is the harvest time of this decode segment's previous tokens
    # (0.0 = unanchored: before the first token, or cleared by
    # preemption so the evict→resume gap never counts as inter-token
    # latency); the accumulators feed itl_mean_ms per request
    admission_ms: float = 0.0
    _itl_anchor: float = 0.0
    _itl_sum_ms: float = 0.0
    _itl_n: int = 0

    def emit(self, chunk: List[int]) -> None:
        if self.stream is not None and chunk:
            self.stream.put(chunk)

    def finish_stream(self) -> None:
        if self.stream is not None:
            self.stream.put(None)


class DecodeEngine:
    """Continuous-batching generation over a fixed slot batch.

    ``generate(params, prompts)`` is thread-safe and blocking — concurrent
    callers' requests join the resident decode at chunk boundaries. Use as
    an ``@model.predictor`` body with ``ServingApp(batch=False)`` (each
    HTTP thread submits directly; batching happens *here*, not in the
    transport).

    Args:
        module: a cache-capable decoder (``unionml_tpu.models.Llama``).
        slots: resident batch size — the max concurrent decodes.
        max_new_tokens: per-request generation cap (requests may ask for
            fewer via ``generate(..., max_new_tokens=n)``).
        prompt_buckets: prompt lengths to compile prefill programs for;
            prompts are left-truncated to the largest bucket. The shared
            cache is sized ``max(buckets) + max_new_tokens +
            (pipeline_depth + 1) * chunk_steps`` — decode attention reads
            all of it every step, so keep the bucket set tight for the
            traffic you serve.
        prefill_chunk: when set, a bucket LARGER than this prefills in
            ``prefill_chunk``-token programs instead of one monolithic
            ``[1, bucket]`` pass. The lead chunks fill a standalone fresh
            cache that never touches the resident state, so the
            dispatcher interleaves DECODE chunks between them — resident
            slots keep streaming tokens while a long prompt admits,
            instead of head-of-line-blocking behind its whole prefill
            (the long-context admission path; VMEM for the prefill
            score buffer is bounded by the chunk, the same knob
            :func:`~unionml_tpu.models.generate.make_generator` uses for
            8k contexts). Only ``ceil(true_len / prefill_chunk)`` chunk
            programs run per admission — a short prompt routed into a
            long bucket pays for its own length, not the bucket's.
            Chunked buckets must divide evenly by ``prefill_chunk``.
        chunk_steps: decode steps per dispatched chunk (join granularity).
        pipeline_depth: max decode chunks in flight before their token
            readbacks are harvested. Size it so ``depth * chunk compute``
            covers the host↔device round trip (a tunneled backend here
            measures ~119 ms RTT vs ~2 ms/step compute, so the default 8
            keeps the device saturated; on a directly attached host 2 is
            plenty and the extra depth is harmless).
        temperature/top_k/top_p/eos_id/pad_id: sampling config, matching
            :func:`~unionml_tpu.models.generate.make_generator`.
        draft_module: a smaller same-vocabulary decoder enabling
            SPECULATIVE decoding: each decode chunk becomes
            ``chunk_steps`` rounds of per-slot draft proposals + ONE
            shared ``[slots, k+1]`` verify forward (amortizing the
            target's weight stream across every resident slot), with
            greedy acceptance advancing per-slot fills —
            token-identical to plain greedy decoding of the target for
            any draft. ``bind``/``generate`` then take the
            ``{"target": ..., "draft": ...}`` params mapping. Greedy
            only; composes with ``system_prefix`` (the prefix rides
            through both models' prefills) but not with
            ``prefix_cache`` (the draft would need a mirrored block
            store). Measured (BASELINE.md round 5): crossover ~25%
            observed acceptance, 1.69× at full, 8B target + 0.3B draft.
        speculate_k: draft tokens proposed per round (k+1 emitted max;
            a round costs k+1 draft steps + one (k+1)-token verify).
        system_prefix: token ids prepended to EVERY request's prompt (a
            shared system prompt). Back-compat shim over the prefix
            cache: the prefix blocks are pinned there, so after the
            first admission computes them they are spliced — never
            re-prefilled — and can never be evicted. Buckets are
            widened by the prefix length (and rounded up to splice
            alignment) internally.
        prefix_cache: a :class:`~unionml_tpu.serving.prefix_cache
            .RadixPrefixCache` (or ``True`` for a default one) enabling
            automatic cross-request prefix reuse: admission splices the
            longest cached block-prefix of the prompt into the slot and
            prefills only the uncovered suffix; completion inserts the
            prompt's KV blocks back. Buckets are rounded up to
            ``lcm(block_size, prefill_chunk)`` multiples so cached
            admissions stay shape-static. One cache per weight binding:
            ``bind`` to different params clears it. Defaults to a
            private cache when ``system_prefix`` is set (the shim),
            else disabled.
        registry/tracer: explicit telemetry sinks
            (:mod:`unionml_tpu.telemetry`). Default to the process-global
            registry and trace recorder, so a ``ServingApp``'s
            ``GET /metrics`` covers this engine automatically and every
            request's ``queue → prefill → decode-chunk[i] → harvest``
            spans land in the exportable trace.
        max_queue_depth: admission control — submissions beyond this
            many queued (not-yet-admitted) requests raise
            :class:`~unionml_tpu.serving.faults.Overloaded` instead of
            queueing unboundedly (the transports map it to HTTP 429
            with ``Retry-After``). ``None`` (default) keeps the
            historical unbounded queue.
        breaker_threshold/breaker_window_s/breaker_cooldown_s: the
            circuit breaker — ``breaker_threshold`` recoveries within
            ``breaker_window_s`` seconds open it for
            ``breaker_cooldown_s`` seconds, during which submissions
            fail fast with :class:`~unionml_tpu.serving.faults
            .EngineUnavailable` and ``health()`` reports ``degraded``
            (a persistently-poisoned device must shed load, not grind
            every request through another doomed rebuild). Any
            successfully completed request closes the failure window.
        fault_injector: a :class:`~unionml_tpu.serving.faults
            .FaultInjector` whose ``engine.prefill`` /
            ``engine.dispatch`` / ``engine.harvest`` /
            ``engine.dequeue`` points this engine fires — the chaos
            harness that makes recovery, shedding, and breaker behavior
            deterministically reproducible in CPU-only tests. ``None``
            (production default) is zero-cost.
        introspect: program introspection + flight recording
            (docs/observability.md). When True (default), every
            compiled program (prefill, decode chunk, splice/extract) is
            wrapped by a :class:`~unionml_tpu.introspection
            .ProgramTracker` — compile events record XLA
            ``cost_analysis()`` flops/bytes and compile time, live MFU/
            roofline gauges land in ``/metrics``, and
            ``stats()["programs"]`` reports per-program hardware truth
            — and request lifecycle events stream into the flight
            recorder. Steady-state overhead is a cache-size read plus
            counter increments per *chunk* dispatch (measured by the
            ``serve_introspection`` bench preset); ``False`` disables
            both for an instrumentation-free engine.
        flight: explicit :class:`~unionml_tpu.telemetry.FlightRecorder`
            for lifecycle events; defaults to the process-global one
            (``GET /debug/flight``). Ignored when ``introspect=False``.
        usage: a :class:`~unionml_tpu.serving.usage.UsageLedger` (or
            ``True`` for a default one on this engine's registry)
            enabling per-tenant usage metering (docs/observability.md
            "Usage metering & cost attribution"): every request's
            queue wait, prefill/cached/decode tokens, attributed
            device-seconds and FLOPs (per-dispatch cost split across
            the live batch by harvested-token share), and — in paged
            mode — KV block-seconds are billed to its tenant (the
            ``X-Tenant-ID`` header via the ambient
            :func:`~unionml_tpu.serving.usage.tenant_scope`, or the
            ``tenant=`` argument of :meth:`generate`). Per-tenant
            aggregates export as bounded-cardinality
            ``unionml_tenant_*`` series; ``None`` (default) disables
            metering entirely — every record site is one attr-is-None
            check (the ``serve_usage`` bench measures the delta).
        perf: the serving goodput plane (docs/observability.md
            "Serving goodput & tail attribution"): every dispatcher
            pass is classified into a bounded ring (full-batch /
            padded-slots / prefill-mix / idle →
            ``unionml_serving_goodput_ratio`` and friends, read by
            ``GET /debug/goodput``), decode-chunk harvests feed the
            ``unionml_engine_itl_ms`` inter-token-latency histograms
            and per-request ITL accumulators, completed requests tag
            the latency histograms with rid exemplars (``GET
            /debug/tail``), and a :class:`~unionml_tpu.serving.perf
            .ServingRegressionWatchdog` watches TTFT/ITL/goodput for
            regressions (``perf_regression`` flight events). ``None``
            (default) enables the plane iff ``introspect`` is on;
            ``False`` disables it (every hook is one attr-is-None
            check — the ``serve_perf`` bench holds the on/off p99
            delta under 1%); an explicit
            :class:`~unionml_tpu.serving.perf.ServingPerfPlane`
            injects one.
        paged/kv_pool_bytes/kv_pool_blocks/kv_block_size: BLOCK-PAGED
            device KV (docs/performance.md "Paged KV attention";
            PagedAttention lineage). Instead of ``slots`` contiguous
            ``cache_len``-row caches, device KV lives in one global
            pool of ``kv_block_size``-token blocks sized by an HBM
            byte budget (``kv_pool_bytes``) or a block count
            (``kv_pool_blocks``; default: the contiguous equivalent,
            a pure layout change), with a per-slot int32 block table
            grown one block at a time as decode proceeds — a short
            prompt in a long bucket charges HBM for its own tokens,
            not the bucket's, so the effective batch at a fixed byte
            budget rises with the traffic's long-tail (the
            ``serve_paged`` bench preset measures it). Admission
            RESERVES a request's worst-case blocks up front (prompt +
            ``max_new_tokens``), so growth can never fail mid-decode:
            a transiently full pool parks the admission until blocks
            free (queued behind it, admission control sheds the
            overflow), and a request that can NEVER fit is rejected
            ``Overloaded`` at submit. Decode attention runs through
            :mod:`~unionml_tpu.ops.paged_attention` (the module
            config's ``paged_impl`` picks kernel vs reference; the
            reference path is bit-identical to the contiguous
            layout). Block size defaults to the prefix cache's (the
            two MUST share one block unit — mismatches raise), else
            16; buckets round to ``lcm(block, prefill_chunk)`` via
            the same ``_block_geometry()`` the prefix cache uses.
            Pool telemetry: ``unionml_kv_pool_*``. Not composable
            with ``draft_module`` (the draft would need its own
            pool).
        scheduler: a :class:`~unionml_tpu.serving.scheduler
            .SchedulerConfig` tuning the PREEMPTIVE, PRIORITY-AWARE
            admission scheduler (docs/robustness.md "Preemption &
            fairness"). Every engine runs the scheduler's waiting
            room: requests carry a priority class (``X-Priority``
            header / ``generate(priority=)``) and admissions drain
            per-(priority, tenant) deficit-weighted queues — a
            single-tenant, single-priority stream degenerates to the
            historical FIFO. Preemption (evicting a strictly
            lower-priority resident's KV blocks to the host
            prefix-cache store so a higher-priority waiter can admit,
            resuming the victim later via the splice path with exact
            token parity) auto-enables when the engine is ``paged``
            AND has a ``prefix_cache`` (the lossless evict/resume
            prerequisites); ``SchedulerConfig(preempt=True)`` makes
            missing prerequisites a construction error instead of a
            silent park-only fallback. ``None`` (default) uses the
            default config.
    """

    def __init__(
        self,
        module,
        *,
        slots: int = 8,
        max_new_tokens: int = 32,
        prompt_buckets: Sequence[int] = (64,),
        prefill_chunk: Optional[int] = None,
        chunk_steps: int = 8,
        pipeline_depth: int = 8,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        eos_id: Optional[int] = None,
        pad_id: int = 0,
        seed: int = 0,
        submit_timeout: float = 300.0,
        system_prefix: Optional[Sequence[int]] = None,
        draft_module=None,
        speculate_k: int = 4,
        prefix_cache=None,
        registry: Optional[telemetry.MetricsRegistry] = None,
        tracer: Optional[telemetry.TraceRecorder] = None,
        max_queue_depth: Optional[int] = None,
        breaker_threshold: int = 3,
        breaker_window_s: float = 30.0,
        breaker_cooldown_s: float = 5.0,
        fault_injector=None,
        introspect: bool = True,
        flight=None,
        usage=None,
        perf=None,
        paged: bool = False,
        kv_pool_bytes: Optional[int] = None,
        kv_pool_blocks: Optional[int] = None,
        kv_block_size: Optional[int] = None,
        scheduler: Optional[SchedulerConfig] = None,
        phase: Optional[str] = None,
    ):
        import jax

        from unionml_tpu.models.generate import make_sampler

        if slots < 1:
            raise ValueError("need at least one slot")
        if not prompt_buckets:
            raise ValueError("need at least one prompt bucket")
        # serving phase (docs/serving.md "Disaggregated serving"):
        # which half of a generative request this engine's pool owns.
        # The engine itself serves any request either way — the label
        # rides health()/stats()/flight events so a phase-split
        # fleet's telemetry is attributable per pool, and the
        # phase-aware router picks by it.
        self.phase = validate_phase(phase)
        # model version currently bound into this engine (docs/
        # robustness.md "Rollouts & rollback"): set by the rollout
        # controller's bind()-then-tag choreography (and by
        # EngineReplica(version=...)), None when nobody versioned the
        # weights. Rides usage vectors so per-tenant billing splits by
        # model version during a canary bake.
        self.model_version: Optional[str] = None
        self.draft = draft_module
        self.speculate_k = int(speculate_k)
        if self.draft is not None:
            # SPECULATIVE engine: per-slot draft proposals + one shared
            # [slots, k+1] verify forward per round, greedy acceptance
            # advancing per-slot fills — token-identical to plain greedy
            # decoding of the target (the make_speculative_generator
            # acceptance rule, restructured for the resident slot batch)
            if temperature != 0.0:
                raise ValueError(
                    "the speculative engine is greedy-only (sampled "
                    "speculation needs the rejection-sampling correction; "
                    "match make_speculative_generator)"
                )
            if prefix_cache not in (None, False):
                raise ValueError(
                    "the speculative engine does not compose with the "
                    "prefix KV-cache yet — the draft model would need a "
                    "mirrored block store; drop prefix_cache "
                    "(system_prefix alone is fine: the prefix rides "
                    "through both prefills)"
                )
            if self.draft.config.vocab_size != module.config.vocab_size:
                raise ValueError(
                    f"target/draft vocabularies differ: "
                    f"{module.config.vocab_size} vs "
                    f"{self.draft.config.vocab_size}"
                )
            if self.speculate_k < 1:
                raise ValueError(f"speculate_k must be >= 1, got {speculate_k}")
            if self.speculate_k + 1 > min(int(b) for b in prompt_buckets):
                # idle slots write k+1 garbage draft/verify rows from
                # their parked fill; admission's full-bucket splice must
                # cover them
                raise ValueError(
                    f"speculate_k + 1 = {self.speculate_k + 1} exceeds the "
                    f"smallest prompt bucket {min(prompt_buckets)}"
                )
        # rows a dispatched chunk can advance a slot: 1 per decode step,
        # or k+1 per speculative round
        self._round_stride = 1 if self.draft is None else self.speculate_k + 1
        self._jax = jax
        self.module = module
        self.cfg = module.config
        self.slots = slots
        self.max_new_tokens = max_new_tokens
        self.prefill_chunk = None if prefill_chunk is None else int(prefill_chunk)
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.chunk_steps = chunk_steps
        self.pipeline_depth = max(1, pipeline_depth)
        self.eos_id = eos_id
        self.pad_id = pad_id
        self.submit_timeout = submit_timeout
        # fault tolerance: admission control + supervision knobs
        if max_queue_depth is not None and max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1 when set")
        if breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")
        self.max_queue_depth = max_queue_depth
        self.breaker_threshold = breaker_threshold
        self.breaker_window_s = breaker_window_s
        self.breaker_cooldown_s = breaker_cooldown_s
        self._faults = fault_injector
        self._draining = False
        self._breaker_open_until = 0.0
        # recovery timestamps within the breaker window (lock-guarded);
        # cleared on any successful completion, so only CONSECUTIVE
        # rebuild failures accumulate toward the threshold
        self._recovery_times: "deque[float]" = deque()
        # bumped by _recover: in-flight readbacks dispatched under an
        # older epoch belong to the poisoned era and are never
        # materialized (their requests were already failed)
        self._epoch = 0
        # telemetry sinks before the cache: a default-constructed cache
        # registers its series in the engine's registry
        self._registry = registry if registry is not None else telemetry.get_registry()
        self._tracer = tracer if tracer is not None else telemetry.get_tracer()
        self.instance = telemetry.instance_label("engine")
        # introspection sinks (None when introspect=False: every record
        # site is a single attr-is-None check — the bench-measured
        # instrumentation-off path)
        self.introspect = bool(introspect)
        self._flight = (
            (flight if flight is not None else telemetry.get_flight_recorder())
            if self.introspect else None
        )
        # usage metering (off-switch: None leaves every record site a
        # single attr check, measured by the serve_usage bench)
        if usage is True:
            from unionml_tpu.serving.usage import UsageLedger

            usage = UsageLedger(registry=self._registry)
        self._usage = usage or None
        # serving goodput plane (docs/observability.md "Serving
        # goodput & tail attribution"): dispatcher-pass classification
        # into the bounded ring, ITL histograms + tail exemplars, and
        # the perf-regression watchdog. Defaults on with introspection
        # (perf=None); ``False`` disables it, an explicit
        # ServingPerfPlane injects one. Every hook below is a single
        # attr-is-None check — the serve_perf bench holds the on/off
        # p99 delta under 1%.
        if perf is None:
            perf = self.introspect
        if perf is True:
            from unionml_tpu.serving.perf import ServingPerfPlane

            perf = ServingPerfPlane(
                registry=self._registry, flight=self._flight,
                engine=self.instance, phase=self.phase,
                slots=self.slots, chunk_steps=self.chunk_steps,
            )
        self._perf = perf or None
        # harvester-thread clock: end of the previous readback, so each
        # entry's attributed device time is the wall it exclusively
        # occupied the device pipeline (consecutive-harvest spacing ==
        # per-chunk device time once the pipeline saturates)
        self._last_harvest_end = 0.0
        self._programs = None
        # shared system prefix (back-compat shim over the prefix cache):
        # the tokens are PREPENDED to every request's prompt and their
        # KV blocks pinned in the cache — the first admission prefills
        # them, every later one splices them, and they can never be
        # evicted. This replaces the old seed-once broadcast programs.
        self._prefix_tokens = (
            None
            if system_prefix is None
            else np.asarray(system_prefix, np.int32).ravel()
        )
        if self._prefix_tokens is not None and self._prefix_tokens.size == 0:
            raise ValueError("system_prefix must be non-empty when given")
        self.prefix_len = (
            0 if self._prefix_tokens is None else len(self._prefix_tokens)
        )
        if (
            prefix_cache is None
            and self._prefix_tokens is not None
            and self.draft is None
        ):
            prefix_cache = True  # the shim keeps old system_prefix reuse
        if prefix_cache is True:
            from unionml_tpu.serving.prefix_cache import RadixPrefixCache

            prefix_cache = RadixPrefixCache(registry=self._registry)
        self.prefix_cache = prefix_cache or None
        if self._prefix_tokens is not None and self.prefix_cache is not None:
            self.prefix_cache.pin(self._prefix_tokens)
        # block-paged device KV: pool geometry resolves through
        # _block_geometry() so the device pool and the prefix cache's
        # host store can never disagree on the block unit
        self.paged = bool(
            paged or kv_pool_bytes is not None or kv_pool_blocks is not None
        )
        if self.paged and self.draft is not None:
            raise ValueError(
                "the speculative engine does not compose with the paged "
                "KV pool yet — the draft model would need a mirrored "
                "pool; drop paged/kv_pool_* or draft_module"
            )
        self._kv_block_size_arg = (
            None if kv_block_size is None else int(kv_block_size)
        )
        if self._kv_block_size_arg is not None and self._kv_block_size_arg < 1:
            raise ValueError("kv_block_size must be >= 1")
        # device-resident LRU of recently-spliced units (dispatcher
        # thread only): a hot prefix — the pinned system_prefix above
        # all — uploads host→device ONCE, not per admission. Entries
        # hold the host block tuples too, so an id() key can never be
        # recycled while its entry lives. The cap bounds device bytes
        # (cap × unit tokens of KV).
        self._dev_splice: "OrderedDict" = OrderedDict()
        self._dev_splice_cap = 8
        # bucket set: the prefix shim widens every bucket by the prefix
        # length (prompts now INCLUDE the prefix), and a shared block
        # unit (prefix cache and/or paged pool — ONE geometry, resolved
        # by _block_geometry) rounds buckets up to lcm(block,
        # prefill_chunk) so cached admissions (block-granularity
        # chunks), paged block scatters, and chunked prefill all keep
        # static, evenly-covered shapes
        self._kv_block_size, align = self._block_geometry()
        raw = sorted(set(int(b) for b in prompt_buckets))
        if self.prefix_len or self.prefix_cache is not None or self.paged:
            raw = sorted(set(
                -(-(b + self.prefix_len) // align) * align for b in raw
            ))
        self.buckets = tuple(raw)
        # per-request prompts are truncated to this BEFORE the prefix is
        # prepended, so the prefix can never be cut by a long prompt
        self._user_max = self.buckets[-1] - self.prefix_len
        if self.prefill_chunk is not None:
            bad = [
                b for b in self.buckets
                if b > self.prefill_chunk and b % self.prefill_chunk
            ]
            if bad:
                raise ValueError(
                    f"buckets {bad} are not multiples of prefill_chunk "
                    f"{self.prefill_chunk} — chunked prefill needs even "
                    "chunk coverage (pad the bucket or change the chunk)"
                )
        # spare rows: a slot may overshoot its token budget by up to the
        # full in-flight window (pipeline_depth chunks dispatched before
        # the host harvests the completion, plus the chunk being
        # dispatched) before the host retires it; sparing those rows keeps
        # the fill invariant (fill always points at a masked-False row)
        # without per-slot write redirection
        self.cache_len = (
            self.buckets[-1]
            + max_new_tokens
            + (self.pipeline_depth + 1) * chunk_steps * self._round_stride
            # a speculative round writes k rows past its counted advance
            + (self._round_stride - 1)
        )
        if self.paged:
            # the logical row space maps exactly onto whole pool blocks
            # (table width = cache_len / block); overshoot rows past a
            # request's reserved blocks write the trash block instead
            self.cache_len = (
                -(-self.cache_len // self._kv_block_size)
                * self._kv_block_size
            )
        max_lens = [self.cfg.max_len] + (
            [self.draft.config.max_len] if self.draft is not None else []
        )
        if self.cache_len > min(max_lens):
            raise ValueError(
                f"cache length {self.cache_len} (= max bucket "
                f"{self.buckets[-1]} incl. any system prefix + "
                f"max_new_tokens {max_new_tokens} + (pipeline_depth "
                f"{self.pipeline_depth} + 1) * chunk_steps {chunk_steps} "
                f"* round stride {self._round_stride} spare rows) exceeds "
                f"model max_len {min(max_lens)}; lower pipeline_depth/"
                "chunk_steps or raise max_len"
            )
        # device block pool (paged mode): host-side free-list allocator
        # + per-slot block tables; the device arrays live in _state
        self.kv_pool: Optional[KVBlockPool] = None
        self._table: Optional[np.ndarray] = None
        self._dispatch_seq = 0      # decode chunks dispatched (fence clock)
        self._harvest_seq = 0       # decode chunks harvested
        # (fence, block ids): freed only once every chunk dispatched
        # before the retirement has been harvested — an in-flight chunk
        # may still write a just-retired slot's rows, and a recycled
        # block must never see them
        self._deferred_free: List = []
        if self.paged:
            blk = self._kv_block_size
            self._table_width = self.cache_len // blk
            block_nbytes = self._kv_block_nbytes(blk)
            if kv_pool_blocks is not None:
                num_blocks = int(kv_pool_blocks)
            elif kv_pool_bytes is not None:
                num_blocks = max(2, int(kv_pool_bytes) // block_nbytes)
            else:
                # default: the contiguous layout's worst case — a pure
                # layout change until a byte budget tightens it
                num_blocks = 1 + slots * self._table_width
            self.kv_pool = KVBlockPool(
                num_blocks=num_blocks, block_size=blk,
                block_nbytes=block_nbytes, registry=self._registry,
            )
            self._table = np.zeros((slots, self._table_width), np.int32)
            self._slot_covered = [0] * slots   # taken blocks per slot row
            self._slot_rows = [0] * slots      # dispatched-rows upper bound
        self._sample = make_sampler(
            temperature=temperature, top_k=top_k, top_p=top_p
        )
        self._key = jax.random.PRNGKey(seed)
        self._params: Any = None
        self._state: Any = None
        self._occupant: List[Optional[_Request]] = [None] * slots
        # bumped on every (re)admission: an in-flight chunk snapshot with a
        # stale generation must not credit its tokens to the new occupant
        self._slot_gen: List[int] = [0] * slots
        # requests popped from the queue but not yet visible in _occupant
        # (admission spans the prefill dispatch): bind()'s busy check must
        # see them or a concurrent swap lands mid-admission
        self._admitting = 0
        # chunked admission in progress (dispatcher thread only); its
        # reserved slot keeps occupant None until the final chunk lands
        self._admission: Optional[_Admission] = None
        # preemptive, priority-aware admission scheduling
        # (docs/robustness.md "Preemption & fairness"): the waiting
        # room replaces the old FIFO queue + single-slot park —
        # per-(priority, tenant) deficit-weighted queues with a
        # bounded parked lane for pool-exhausted admissions
        sched_cfg = scheduler if scheduler is not None else SchedulerConfig()
        can_preempt = self.paged and self.prefix_cache is not None
        if sched_cfg.preempt and not can_preempt:
            raise ValueError(
                "SchedulerConfig(preempt=True) needs a paged engine "
                "with a prefix cache — eviction extracts the victim's "
                "pool blocks into the host prefix-cache store and "
                "resume splices them back (pointer swaps, exact token "
                "parity); pass paged=True and prefix_cache=..."
            )
        self._preempt_enabled = (
            can_preempt if sched_cfg.preempt is None else bool(sched_cfg.preempt)
        )
        self._mix_budget = sched_cfg.mix_prefill_tokens
        self._sched = PreemptiveScheduler(
            sched_cfg, registry=self._registry,
            engine_label=self.instance, usage=self._usage,
            phase=self.phase,
        )
        self._room = self._sched.room
        self._lock = threading.Lock()
        # dispatch→harvest pipeline: FIFO of in-flight readbacks; the
        # semaphore caps chunk entries at pipeline_depth
        self._inflight: "queue.Queue" = queue.Queue()
        self._chunk_credits = threading.Semaphore(self.pipeline_depth)
        # observability: every tally lives in the shared telemetry
        # registry (one scrape surface across engine/batcher/HTTP/
        # trainer); stats() is a thin view over these instruments. The
        # instance label keeps concurrent engines' series separate.
        # (registry/tracer/instance were resolved above, before the
        # prefix cache registered its own series.)
        self._build_instruments()
        # harvest-span anchor: set at the top of each _process_entry
        # (harvester thread only), read by _finish_if_done under the lock
        self._harvest_t0 = 0.0
        self._build_programs()
        if self.introspect:
            self._instrument_programs()
        self._stop = threading.Event()
        self._worker = threading.Thread(
            target=self._run, daemon=True, name="unionml-tpu-decode-engine"
        )
        self._harvester = threading.Thread(
            target=self._harvest_loop, daemon=True,
            name="unionml-tpu-decode-harvest",
        )
        self._worker.start()
        self._harvester.start()

    def _build_instruments(self):
        """Register this instance's metric series (get-or-create: the
        family schemas are shared, the ``engine`` label isolates us)."""
        R, lbl = self._registry, {"engine": self.instance}

        def counter(name, help):
            return R.counter(name, help, ("engine",)).labels(**lbl)

        def hist(name, help):
            return R.histogram(name, help, ("engine",)).labels(**lbl)

        self._m_requests = counter(
            "unionml_engine_requests_total",
            "Requests completed and delivered to their waiter.",
        )
        self._m_errors = counter(
            "unionml_engine_errors_total",
            "Requests failed by an engine/admission error.",
        )
        self._m_abandoned = counter(
            "unionml_engine_abandoned_total",
            "Requests whose waiter gave up before completion.",
        )
        self._m_timeouts = counter(
            "unionml_engine_timeouts_total",
            "generate()/generate_stream() waits that hit submit_timeout.",
        )
        self._m_steps = counter(
            "unionml_engine_decode_steps_total",
            "Decode steps dispatched (all slots advance together).",
        )
        self._m_chunks = counter(
            "unionml_engine_chunks_total", "Decode chunks dispatched.",
        )
        self._m_occupied = counter(
            "unionml_engine_occupied_slot_steps_total",
            "Slot-steps dispatched with a live occupant (occupancy "
            "numerator; denominator is decode_steps * slots).",
        )
        self._m_slots_busy = R.gauge(
            "unionml_engine_slots_in_use",
            "Slots currently holding a live request.", ("engine",),
        ).labels(**lbl)
        R.gauge(
            "unionml_engine_slots", "Resident decode slots.", ("engine",)
        ).labels(**lbl).set(self.slots)
        self._h_queue = hist(
            "unionml_engine_queue_wait_ms",
            "Submit-to-admission wait per completed request.",
        )
        self._h_prefill = hist(
            "unionml_engine_prefill_ms",
            "Prefill dispatch-to-first-token-harvest per completed request.",
        )
        self._h_decode = hist(
            "unionml_engine_decode_ms",
            "First-token-to-retirement decode time per completed request.",
        )
        self._h_ttft = hist(
            "unionml_engine_ttft_ms",
            "Submit-to-first-harvested-token per completed request.",
        )
        self._h_dispatch = hist(
            "unionml_engine_chunk_dispatch_ms",
            "Host time to enqueue one decode chunk (sampler keys + jit "
            "call; the dispatcher's per-chunk cost).",
        )
        self._h_harvest = hist(
            "unionml_engine_chunk_harvest_ms",
            "Blocking readback + accounting per harvested decode chunk "
            "(includes in-flight pipeline lag).",
        )
        self._m_spec_rounds = counter(
            "unionml_engine_spec_rounds_total",
            "Speculative rounds whose tokens were served.",
        )
        self._m_spec_accepted = counter(
            "unionml_engine_spec_accepted_tokens_total",
            "Draft tokens accepted by the target verify forward.",
        )
        # fault tolerance: admission control / supervision series
        rejected = R.counter(
            "unionml_engine_rejected_total",
            "Submissions rejected at admission control, by reason "
            "(queue_full -> 429, breaker_open/draining -> 503).",
            ("engine", "reason"),
        )
        self._m_rejected = {
            reason: rejected.labels(engine=self.instance, reason=reason)
            for reason in (
                "queue_full", "breaker_open", "draining", "pool_full",
            )
        }
        self._m_deadline_shed = counter(
            "unionml_engine_deadline_shed_total",
            "Requests shed at dequeue because their deadline expired "
            "before prefill (no device work burned).",
        )
        self._m_recoveries = counter(
            "unionml_engine_recoveries_total",
            "Supervised recoveries: a failed device program failed only "
            "its poisoned batch and the decode state was rebuilt.",
        )
        self._g_breaker = R.gauge(
            "unionml_engine_breaker_open",
            "1 while the circuit breaker rejects submissions.",
            ("engine",),
        ).labels(**lbl)
        self._g_queue_depth = R.gauge(
            "unionml_engine_queue_depth",
            "Requests queued awaiting admission.", ("engine",),
        ).labels(**lbl)
        self._h_drain = hist(
            "unionml_engine_drain_ms",
            "drain() wall time: stop-admissions to queue+slots idle.",
        )
        # per-token attribution (the serving goodput plane): chunk
        # harvest spacing over the chunk's harvested tokens, split by
        # priority class — observed only while the perf plane is on,
        # so a plane-off engine records nothing here. Children are
        # pre-resolved: the harvester must not pay the family-lock
        # labels() lookup per chunk.
        itl = R.histogram(
            "unionml_engine_itl_ms",
            "Inter-token latency per harvested decode chunk (harvest "
            "spacing / tokens in the chunk), by priority class.",
            ("engine", "phase", "priority"),
        )
        self._h_itl = {
            p: itl.labels(
                engine=self.instance, phase=self.phase, priority=p
            )
            for p in PRIORITIES
        }

    def _instrument_programs(self):
        """Wrap the compiled hot-path programs in a cost-analysis
        tracker (docs/observability.md): compile events record XLA
        flops/bytes + compile time per program key, dispatches feed the
        MFU/roofline gauges, and ``stats()["programs"]`` becomes the
        hardware-truth view. The sig lambdas are deliberately ONE shape
        attribute each — they run per dispatch and exist only to tell a
        program's bucketed executables apart."""
        from unionml_tpu.introspection import ProgramTracker

        tr = ProgramTracker(registry=self._registry, component=self.instance)
        self._programs = tr
        self._init_state = tr.wrap("engine.init_state", self._init_state)
        if self.paged:
            # paged programs carry the block-id vector before the
            # tokens, and extraction is table-addressed
            self._prefill = tr.wrap(
                "engine.prefill", self._prefill,
                sig_fn=lambda p, st, slot, ids, toks, *a, **k: toks.shape,
            )
            self._prefill_final = tr.wrap(
                "engine.prefill_final", self._prefill_final,
                sig_fn=lambda p, st, fresh, slot, ids, toks, *a, **k:
                    toks.shape,
            )
        else:
            self._prefill = tr.wrap(
                "engine.prefill", self._prefill,
                sig_fn=lambda p, st, slot, toks, *a, **k: toks.shape,
            )
            self._prefill_final = tr.wrap(
                "engine.prefill_final", self._prefill_final,
                sig_fn=lambda p, st, fresh, slot, toks, *a, **k: toks.shape,
            )
        self._prefill_step = tr.wrap(
            "engine.prefill_chunk", self._prefill_step,
            sig_fn=lambda p, fresh, toks, start: toks.shape,
        )
        self._decode_chunk = tr.wrap("engine.decode", self._decode_chunk)
        self._init_fresh = tr.wrap(
            "engine.init_fresh", self._init_fresh,
            sig_fn=lambda **k: k.get("bucket"),
        )
        if self.prefix_cache is not None:
            self._splice_block = tr.wrap(
                "engine.splice_block", self._splice_block,
                sig_fn=lambda fresh, rows, start: rows[0][0].shape,
            )
            if self.paged:
                self._extract_blocks = tr.wrap(
                    "engine.extract_blocks", self._extract_blocks,
                    sig_fn=lambda pool, ids: ids.shape,
                )
            else:
                self._extract_rows = tr.wrap(
                    "engine.extract_rows", self._extract_rows,
                    sig_fn=lambda cache, slot, **k: k.get("n"),
                )

    def _flight_rec(self, kind: str, **fields) -> None:
        """O(1) flight-recorder append (no-op when introspect=False).
        numpy scalars (slot indices from mask walks) become plain ints
        so a dumped event is always JSON-safe."""
        if self._flight is not None:
            # phase-split fleets tag every lifecycle event with the
            # pool that recorded it (colocated engines stay untagged —
            # the historical event shape is unchanged for them)
            tag = {} if self.phase == "colocated" else {"phase": self.phase}
            self._flight.record(kind, engine=self.instance, **tag, **{
                k: (v.item() if isinstance(v, np.generic) else v)
                for k, v in fields.items()
            })

    def _slots_in_use_locked(self) -> int:
        """Occupied-slot count; call with the lock held."""
        return sum(1 for r in self._occupant if r is not None)

    def _fire(self, point: str) -> None:
        """Chaos-injection site (zero-cost without an injector)."""
        if self._faults is not None:
            self._faults.fire(point)

    @property
    def usage(self):
        """The engine's :class:`~unionml_tpu.serving.usage.UsageLedger`
        (``None`` when metering is off) — share it with the
        ``ServingApp`` so ``GET /debug/usage`` serves this engine's
        per-tenant resource vectors."""
        return self._usage

    @usage.setter
    def usage(self, ledger) -> None:
        """Swap the metering seam on a live engine — ONLY while idle
        (no request in flight), or a request's vector straddles two
        ledgers. The ``serve_usage`` bench toggles this between its
        overhead legs so both run on the SAME engine instance (two
        separately-constructed engines differ by several percent from
        thread/allocator placement alone, swamping a 2% bar); the
        attribution window is clamped at each chunk's dispatch time,
        so the off-leg's idle gap never inflates the first on-leg
        window."""
        self._usage = ledger or None

    @property
    def perf(self):
        """The engine's :class:`~unionml_tpu.serving.perf
        .ServingPerfPlane` (``None`` when the goodput plane is off) —
        ``GET /debug/goodput`` reads it via :meth:`goodput_report`."""
        return self._perf

    @perf.setter
    def perf(self, plane) -> None:
        """Swap the goodput plane on a live engine — ONLY while idle,
        like the ``usage`` seam above. The ``serve_perf`` bench
        toggles this between its paired overhead legs so both run on
        the SAME engine instance (two separately-constructed engines
        differ by several percent from thread/allocator placement
        alone, swamping a 1% bar)."""
        self._perf = plane or None
        # the waiting room's fair-share weighting follows the swap
        self._room._usage = self._usage

    @property
    def registry(self):
        """The engine's :class:`~unionml_tpu.telemetry.MetricsRegistry`
        — the fleet router's metrics federation reads it to expose this
        replica's series under the router's ``replica`` label (or to
        skip the merge when the replica already shares the router
        app's registry)."""
        return self._registry

    @property
    def tracer(self):
        """The engine's :class:`~unionml_tpu.telemetry.TraceRecorder`
        — the stitched ``/debug/trace`` fetches this replica's request
        timelines through it (identity with the router app's recorder
        means the local merge already covers them)."""
        return self._tracer

    @property
    def flight(self):
        """The engine's :class:`~unionml_tpu.telemetry.FlightRecorder`
        (``None`` when disabled) — the fleet ``/debug/flight`` merge
        reads replica rings through it."""
        return self._flight

    @property
    def breaker_open(self) -> bool:
        """True while the circuit breaker rejects submissions (the
        cooldown after ``breaker_threshold`` recoveries in the window).
        Reading it keeps the ``unionml_engine_breaker_open`` gauge
        honest — the breaker closes by TIME passing, not by an event."""
        is_open = time.monotonic() < self._breaker_open_until
        self._g_breaker.set(1.0 if is_open else 0.0)
        return is_open

    def _gated_submit(self, reqs: List[_Request]) -> None:
        """Admission control + enqueue, atomically under the engine
        lock (shared by ``generate`` and ``generate_stream``): reject
        BEFORE any request is enqueued, so a multi-prompt call never
        partially admits — and so N concurrent submitters cannot each
        pass a depth check and push the queue past ``max_queue_depth``
        (the exact overload the bound exists for)."""
        with self._lock:
            self._admission_gate_locked(reqs)
            for req in reqs:
                # recorded BEFORE the put, inside the lock: a request's
                # 'submit' flight event can never land after its
                # 'prefill' in the trail. queue_depth = requests ahead.
                self._flight_rec(
                    "submit", rid=req.rid, tenant=req.tenant,
                    priority=req.priority,
                    prompt_tokens=len(req.prompt),
                    queue_depth=self._room.qsize(),
                )
                self._room.put(req)
        self._g_queue_depth.set(self._room.qsize())

    def _usage_rejected(self, reqs: List[_Request], reason: str) -> None:
        """Tenant dimension on admission-control rejections (all reqs
        in one submit share a tenant — one gated call per generate)."""
        if self._usage is not None and reqs:
            self._usage.record_rejected(reqs[0].tenant, reason, len(reqs))

    def _admission_gate_locked(self, reqs: List[_Request]) -> None:
        n_new = len(reqs)
        tenant = reqs[0].tenant if reqs else DEFAULT_TENANT
        if self.paged:
            # a request whose worst case exceeds the WHOLE pool can
            # never be admitted — reject now (transient fullness parks
            # at admission instead; the queue bound sheds the backlog)
            for req in reqs:
                needed = self.kv_pool.blocks_for_rows(
                    min(len(req.prompt) + req.max_new_tokens,
                        self.cache_len)
                )
                if needed > self.kv_pool.capacity:
                    self._m_rejected["pool_full"].inc(n_new)
                    self._usage_rejected(reqs, "pool_full")
                    self._flight_rec(
                        "reject", reason="pool_full", n=n_new,
                        tenant=tenant, needed_blocks=needed,
                        capacity_blocks=self.kv_pool.capacity,
                    )
                    raise Overloaded(
                        f"kv pool can never fit this request: "
                        f"{needed} blocks needed "
                        f"({len(req.prompt)} prompt + "
                        f"{req.max_new_tokens} new tokens), pool "
                        f"capacity {self.kv_pool.capacity} blocks",
                        retry_after_s=60.0,
                    )
        if self._draining:
            self._m_rejected["draining"].inc(n_new)
            self._usage_rejected(reqs, "draining")
            self._flight_rec(
                "reject", reason="draining", n=n_new, tenant=tenant,
            )
            raise EngineUnavailable(
                "decode engine is draining and not accepting requests",
                reason="draining", retry_after_s=1.0,
            )
        remaining = self._breaker_open_until - time.monotonic()
        if remaining > 0:
            self._m_rejected["breaker_open"].inc(n_new)
            self._usage_rejected(reqs, "breaker_open")
            self._flight_rec(
                "reject", reason="breaker_open", n=n_new, tenant=tenant,
            )
            raise EngineUnavailable(
                "decode engine circuit breaker is open "
                f"({len(self._recovery_times)} recent recovery failures); "
                f"retry in {remaining:.1f}s",
                reason="breaker_open", retry_after_s=max(0.1, remaining),
            )
        if self.max_queue_depth is not None:
            depth = self._room.qsize()
            if depth + n_new > self.max_queue_depth:
                self._m_rejected["queue_full"].inc(n_new)
                self._usage_rejected(reqs, "queue_full")
                self._flight_rec(
                    "reject", reason="queue_full", n=n_new,
                    tenant=tenant, queue_depth=depth,
                )
                raise Overloaded(
                    f"decode engine queue is full ({depth} queued + "
                    f"{n_new} new > max_queue_depth "
                    f"{self.max_queue_depth})",
                    retry_after_s=1.0,
                )

    def health(self) -> dict:
        """Readiness surface for ``GET /health``: ``status`` is ``ok``,
        ``degraded`` (circuit breaker open), or ``draining``; plus the
        queue depth and breaker state the transports report."""
        breaker = self.breaker_open
        if self._draining:
            status = "draining"
        elif breaker:
            status = "degraded"
        else:
            status = "ok"
        out = {
            "status": status,
            "queue_depth": self._room.qsize(),
            "breaker_open": breaker,
        }
        if self.phase != "colocated":
            out["phase"] = self.phase
        return out

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful drain: stop admitting (new submissions raise
        :class:`~unionml_tpu.serving.faults.EngineUnavailable` and
        ``health()`` flips to ``draining``), then block until every
        queued and in-flight request — streams included — has finished
        and all readbacks are harvested. Returns True when fully
        drained, False on ``timeout`` (work may still be in flight;
        admissions stay stopped either way). Reversible with
        :meth:`resume`; observability lands in the
        ``unionml_engine_drain_ms`` histogram."""
        t0 = time.perf_counter()
        self._draining = True
        drained = False
        while True:
            with self._lock:
                drained = (
                    self._room.empty()
                    and self._admitting == 0
                    and self._admission is None
                    and all(r is None for r in self._occupant)
                    and self._inflight.empty()
                )
            if drained:
                break
            if (
                timeout is not None
                and time.perf_counter() - t0 > timeout
            ):
                break
            time.sleep(0.005)
        self._h_drain.observe((time.perf_counter() - t0) * 1e3)
        return drained

    def resume(self) -> None:
        """Reopen admissions after :meth:`drain` (rolling-restart flows
        that drain, swap weights via :meth:`bind`, and serve again)."""
        self._draining = False

    def _block_geometry(self):
        """The SINGLE home for KV block geometry: ``(block, align)``.

        ``block`` is the shared block unit of the paged device pool AND
        the prefix cache's host store — the two must agree (splice and
        extract are per-block copies addressed by table entries), so an
        explicit ``kv_block_size`` that contradicts the attached prefix
        cache raises instead of silently desyncing. ``align`` is the
        bucket rounding unit, ``lcm(block, prefill_chunk)`` — applied
        whenever ANY block consumer is configured (prefix cache, paged
        pool, or the prefix shim), so paged and prefix-cache bucket
        geometry can never disagree either."""
        cache_blk = (
            self.prefix_cache.block_size
            if self.prefix_cache is not None else None
        )
        pool_blk = self._kv_block_size_arg if self.paged else None
        if (
            pool_blk is not None
            and cache_blk is not None
            and pool_blk != cache_blk
        ):
            raise ValueError(
                f"kv_block_size {pool_blk} != prefix cache block_size "
                f"{cache_blk} — the device pool and the host block store "
                "share one block unit (admission splice and harvest "
                "extract are per-block copies); drop kv_block_size or "
                "rebuild the cache with the matching block_size"
            )
        block = pool_blk or cache_blk or (16 if self.paged else None)
        align = block or 1
        if self.prefill_chunk is not None:
            align = math.lcm(align, self.prefill_chunk)
        return block, align

    def _kv_block_nbytes(self, blk: int) -> int:
        """Device bytes of one pool block across every layer and buffer
        (mirrors ``init_cache``'s layout: bf16 k/v, or int8 k/v + fp32
        per-(row, head) scales under ``kv_quant``)."""
        cfg = self.cfg
        rows = blk * cfg.num_kv_heads
        if getattr(cfg, "kv_quant", False):
            per_layer = 2 * (rows * cfg.head_dim * 1 + rows * 4)
        else:
            per_layer = 2 * rows * cfg.head_dim * 2
        return cfg.num_layers * per_layer

    # ------------------------------------------------------------------ #
    # device programs (compiled once per shape)
    # ------------------------------------------------------------------ #

    def _build_programs(self):
        import jax
        import jax.numpy as jnp

        from unionml_tpu.models.llama import init_cache

        if self.draft is not None:
            self._build_spec_programs()
            return
        if self.paged:
            self._build_paged_programs()
            return

        cfg, L, B = self.cfg, self.cache_len, self.slots
        module, sample = self.module, self._sample
        eos_id, pad_id = self.eos_id, self.pad_id

        def init_state():
            return {
                "cache": init_cache(cfg, B, L),
                "kv_mask": jnp.zeros((B, L), bool),
                # empty slots idle at row 0: dead slots still run the
                # decode apply and write garbage k/v at their fill row —
                # row 0 stays masked False and is overwritten by the
                # next admission's full-bucket splice
                "fill": jnp.zeros((B,), jnp.int32),
                "last_tok": jnp.zeros((B,), jnp.int32),
                "done": jnp.ones((B,), bool),
            }

        self._init_state = jax.jit(init_state)

        import functools

        def finish_prefill(params, state, fresh, slot, toks, start, true_len,
                           key, **apply_kwargs):
            """The SINGLE home for the prefill tail (monolithic, chunked,
            and prefix-cached admissions all trace it — a desynced
            invariant here would corrupt one path silently): run ``toks``
            (the whole right-padded bucket at ``start=0``, or the final
            chunk at its offset) against ``fresh``, sample the first
            token at the last REAL position, splice the whole fresh
            cache into ``slot`` — cached-prefix rows spliced before the
            chunks ran are carried along; garbage rows above ``true_len``
            stay masked False in the resident kv_mask."""
            bucket = fresh[0][0].shape[1]
            c = toks.shape[1]
            kv_mask = (jnp.arange(bucket) < true_len)[None, :]
            logits, filled = module.apply(
                {"params": params}, toks,
                positions=start + jnp.arange(c)[None, :],
                cache=fresh, cache_index=start, kv_mask=kv_mask,
                # head on the last REAL position only — the full-bucket
                # head would materialize [1, bucket, vocab] fp32
                logit_index=jnp.reshape(true_len - 1 - start, (1,)),
                **apply_kwargs,
            )
            first = sample(logits[:, 0], key)[0]
            cache = _splice_rows(state["cache"], filled, slot, 0)
            row_mask = jnp.arange(L) < true_len
            return {
                "cache": cache,
                "kv_mask": state["kv_mask"].at[slot].set(row_mask),
                "fill": state["fill"].at[slot].set(true_len),
                "last_tok": state["last_tok"].at[slot].set(first),
                "done": state["done"].at[slot].set(False),
            }, first

        # a monolithic admission covers the whole visible history, so
        # cfg.prefill_impl == "flash" may run it through the flash
        # kernel (right-padded buckets need no pad mask: causal alone
        # hides the trailing garbage). Chunked and prefix-cached
        # admissions keep the cached path.
        _full_kwargs = (
            {"full_prefill": True} if cfg.prefill_impl == "flash" else {}
        )

        def prefill(params, state, slot, tokens, true_len, key):
            """Monolithic admission: fresh build + full-bucket finish in
            ONE program (short buckets; one dispatch per admission)."""
            fresh = init_cache(cfg, 1, tokens.shape[0])
            return finish_prefill(
                params, state, fresh, slot, tokens[None], jnp.int32(0),
                true_len, key, **_full_kwargs,
            )

        self._prefill = jax.jit(prefill, donate_argnums=(1,))

        # ---- chunked prefill (long buckets): lead chunks fill a fresh
        # [1, bucket] cache WITHOUT touching the resident state, so
        # decode chunks interleave between them; only the final chunk
        # (finish_prefill) splices into the slot and samples token 0.
        # Prefix-cached admissions ride the same machinery with
        # chunk = the cache block size and the leading chunks replaced
        # by host-row splices. ----

        @functools.partial(jax.jit, static_argnames=("bucket",))
        def init_fresh(*, bucket):
            return init_cache(cfg, 1, bucket)

        self._init_fresh = init_fresh

        def prefill_step(params, fresh, toks, start):
            """One lead chunk: tokens are fully real (the host only runs
            chunks covering the true length; the final, possibly padded,
            chunk goes through ``finish_prefill``)."""
            lf = fresh[0][0].shape[1]          # bucket (static)
            c = toks.shape[1]
            kv_mask = (jnp.arange(lf) < start + c)[None, :]
            _, fresh = module.apply(
                {"params": params}, toks,
                positions=start + jnp.arange(c)[None, :],
                cache=fresh, cache_index=start, kv_mask=kv_mask,
                # head output unused → DCE'd; the chunk only fills cache
                logit_index=jnp.zeros((1,), jnp.int32),
            )
            return fresh

        self._prefill_step = jax.jit(prefill_step, donate_argnums=(1,))
        # donate the resident state only: no output matches the fresh
        # cache's [1, bucket] shape, so donating it would just warn
        self._prefill_final = jax.jit(finish_prefill, donate_argnums=(1,))
        self._build_cache_programs()

        def decode_chunk(params, state, active, keys):
            """``chunk_steps`` decode steps for every slot in one scan."""

            def step(state, key):
                live = active & ~state["done"]
                fill = state["fill"]
                # this step writes its k/v at row `fill`; the new token
                # must see ITSELF, so expose the row before the apply —
                # for live slots only (dead slots' writes land on
                # masked-False rows and stay invisible)
                kv_mask = state["kv_mask"] | (
                    (jnp.arange(L)[None, :] == fill[:, None]) & live[:, None]
                )
                logits, cache = module.apply(
                    {"params": params}, state["last_tok"][:, None],
                    cache=state["cache"], cache_index=fill,
                    kv_mask=kv_mask,
                )
                nxt = sample(logits[:, -1], key)
                nxt = jnp.where(live, nxt, pad_id)
                done = state["done"]
                if eos_id is not None:
                    done = done | (live & (nxt == eos_id))
                advance = live & (fill + 1 < L)
                # belt: a live slot at the cache end freezes its fill on a
                # masked-True row — mark done so it stops writing there
                done = done | (live & ~advance)
                return {
                    "cache": cache,
                    "kv_mask": kv_mask,
                    "fill": fill + advance.astype(jnp.int32),
                    "last_tok": jnp.where(live, nxt, state["last_tok"]),
                    "done": done,
                }, nxt

            state, toks = jax.lax.scan(step, state, keys)
            return state, toks  # toks: [chunk_steps, slots]

        self._decode_chunk = jax.jit(decode_chunk, donate_argnums=(1,))

    def _build_paged_programs(self):
        """Paged-mode device programs (``self.paged``).

        Same attribute names and dispatcher contract as the contiguous
        builders, but the resident KV is a global block pool
        (``[num_blocks, block, kv_heads, head_dim]`` per layer) plus the
        host-owned block table passed into every decode chunk:

        - prefill still computes against a transient contiguous
          ``[1, bucket]`` fresh cache (chunked prefill and prefix-cache
          splices ride it unchanged — one admission's workspace, not
          per-slot residency), but ``finish_prefill`` ends in a
          TABLE-DIRECTED per-block scatter into the pool instead of a
          contiguous row splice: only ``ceil(true_len / block)`` real
          blocks are written, padding blocks land on the trash block;
        - the decode chunk reads/writes through
          :mod:`~unionml_tpu.ops.paged_attention` (``block_table=``
          path in the model), with retired slots' table rows masked to
          the trash block PER STEP so an in-flight chunk can never
          corrupt a recycled block;
        - harvest extract gathers a slot's blocks by table entry
          (``jnp.take``), feeding the prefix cache per-block host
          copies directly.

        There is no resident ``kv_mask``: visibility is ``fill + 1``
        (bit-identical to the contiguous mask for live slots — tested).
        """
        import functools

        import jax
        import jax.numpy as jnp

        from unionml_tpu.models.llama import init_cache

        cfg, L, B = self.cfg, self.cache_len, self.slots
        blk = self._kv_block_size
        n_pool = self.kv_pool.num_blocks
        module, sample = self.module, self._sample
        eos_id, pad_id = self.eos_id, self.pad_id

        def init_state():
            return {
                "pool": init_cache(cfg, n_pool, blk),
                # empty slots idle at row 0 with all-trash table rows:
                # dead slots still run the decode apply, but their
                # writes land in the trash block (step_table masking)
                "fill": jnp.zeros((B,), jnp.int32),
                "last_tok": jnp.zeros((B,), jnp.int32),
                "done": jnp.ones((B,), bool),
            }

        self._init_state = jax.jit(init_state)

        def scatter_blocks(pool, fresh, ids):
            """Table-directed block scatter: fresh ``[1, bucket]`` rows
            into pool blocks ``ids`` ([bucket/block] int32; padding
            entries point at the trash block — duplicate trash writes
            race benignly, it is garbage by definition)."""
            nb = ids.shape[0]
            return tuple(
                tuple(
                    pbuf.at[ids].set(
                        fbuf.reshape((nb, blk) + fbuf.shape[2:])
                        .astype(pbuf.dtype)
                    )
                    for pbuf, fbuf in zip(p_layer, f_layer)
                )
                for p_layer, f_layer in zip(pool, fresh)
            )

        def finish_prefill(params, state, fresh, slot, ids, toks, start,
                           true_len, key, **apply_kwargs):
            """The paged prefill tail: same fresh-cache compute and
            first-token sampling as the contiguous path (logits are
            bit-identical), then the per-block pool scatter in place of
            the contiguous row splice."""
            bucket = fresh[0][0].shape[1]
            c = toks.shape[1]
            kv_mask = (jnp.arange(bucket) < true_len)[None, :]
            logits, filled = module.apply(
                {"params": params}, toks,
                positions=start + jnp.arange(c)[None, :],
                cache=fresh, cache_index=start, kv_mask=kv_mask,
                logit_index=jnp.reshape(true_len - 1 - start, (1,)),
                **apply_kwargs,
            )
            first = sample(logits[:, 0], key)[0]
            pool = scatter_blocks(state["pool"], filled, ids)
            return {
                "pool": pool,
                "fill": state["fill"].at[slot].set(true_len),
                "last_tok": state["last_tok"].at[slot].set(first),
                "done": state["done"].at[slot].set(False),
            }, first

        _full_kwargs = (
            {"full_prefill": True} if cfg.prefill_impl == "flash" else {}
        )

        def prefill(params, state, slot, ids, tokens, true_len, key):
            fresh = init_cache(cfg, 1, tokens.shape[0])
            return finish_prefill(
                params, state, fresh, slot, ids, tokens[None],
                jnp.int32(0), true_len, key, **_full_kwargs,
            )

        self._prefill = jax.jit(prefill, donate_argnums=(1,))

        @functools.partial(jax.jit, static_argnames=("bucket",))
        def init_fresh(*, bucket):
            return init_cache(cfg, 1, bucket)

        self._init_fresh = init_fresh

        def prefill_step(params, fresh, toks, start):
            """One lead chunk against the contiguous fresh cache —
            verbatim the contiguous engine's program (the workspace
            layout did not change, only residency did)."""
            lf = fresh[0][0].shape[1]
            c = toks.shape[1]
            kv_mask = (jnp.arange(lf) < start + c)[None, :]
            _, fresh = module.apply(
                {"params": params}, toks,
                positions=start + jnp.arange(c)[None, :],
                cache=fresh, cache_index=start, kv_mask=kv_mask,
                logit_index=jnp.zeros((1,), jnp.int32),
            )
            return fresh

        self._prefill_step = jax.jit(prefill_step, donate_argnums=(1,))
        self._prefill_final = jax.jit(finish_prefill, donate_argnums=(1,))
        self._build_cache_programs()

        def extract_blocks(pool, ids):
            """Gather a slot's pool blocks ([n_blocks, block, ...] per
            buffer) for the async device→host prefix-cache insert —
            per-block copies addressed by table entries (the contiguous
            path's row-window slice has no paged equivalent)."""
            return tuple(
                tuple(jnp.take(buf, ids, axis=0) for buf in layer)
                for layer in pool
            )

        self._extract_blocks = jax.jit(extract_blocks)

        def decode_chunk(params, state, active, table, keys):
            """``chunk_steps`` paged decode steps in one scan. The
            block table is a per-chunk INPUT (the host grows it between
            chunks), with retired/dead slots' rows re-masked to the
            trash block every step so their writes can never land in a
            block the allocator has recycled."""

            def step(state, key):
                live = active & ~state["done"]
                fill = state["fill"]
                step_table = jnp.where(live[:, None], table, 0)
                logits, pool = module.apply(
                    {"params": params}, state["last_tok"][:, None],
                    cache=state["pool"], cache_index=fill,
                    block_table=step_table,
                )
                nxt = sample(logits[:, -1], key)
                nxt = jnp.where(live, nxt, pad_id)
                done = state["done"]
                if eos_id is not None:
                    done = done | (live & (nxt == eos_id))
                advance = live & (fill + 1 < L)
                done = done | (live & ~advance)
                return {
                    "pool": pool,
                    "fill": fill + advance.astype(jnp.int32),
                    "last_tok": jnp.where(live, nxt, state["last_tok"]),
                    "done": done,
                }, nxt

            state, toks = jax.lax.scan(step, state, keys)
            return state, toks  # toks: [chunk_steps, slots]

        self._decode_chunk = jax.jit(decode_chunk, donate_argnums=(1,))

    def _build_cache_programs(self):
        """Prefix-cache device programs (cache-enabled engines only):

        - ``_splice_block``: write one cached splice unit's host rows
          into a fresh ``[1, bucket]`` cache at a dynamic row offset
          (compiled once per (bucket, unit) shape; the host→device copy
          happens once per unit via the ``_dev_splice`` memo).
        - ``_extract_rows``: slice a slot's leading ``n`` resident rows
          in ONE dispatch (compiled once per bucket), feeding the async
          device→host insert path — the harvester splits the contiguous
          copy into blocks host-side.

        Both are rank-generic over the cache tree like
        :func:`_splice_rows`, so int8-KV scale planes ride along."""
        if self.prefix_cache is None:
            return
        import functools

        import jax

        def splice_block(fresh, rows, start):
            return _splice_rows(fresh, rows, 0, start)

        self._splice_block = jax.jit(splice_block, donate_argnums=(0,))

        @functools.partial(jax.jit, static_argnames=("n",))
        def extract_rows(cache, slot, *, n):
            return tuple(
                tuple(
                    jax.lax.dynamic_slice(
                        buf, (slot, 0) + (0,) * (buf.ndim - 2),
                        (1, n) + buf.shape[2:],
                    )
                    for buf in layer
                )
                for layer in cache
            )

        self._extract_rows = extract_rows

    def _build_spec_programs(self):
        """Speculative-mode device programs (``draft_module`` set).

        Same attribute names and call signatures as the plain builders so
        the dispatcher/admission machinery is shared verbatim; ``params``
        is the bound ``{"target", "draft"}`` mapping, fresh caches are
        ``(target, draft)`` pairs, and the decode chunk is a scan of
        ``chunk_steps`` SPECULATIVE ROUNDS: per-slot draft proposals
        (vector ``cache_index``), ONE shared [slots, k+1] verify forward,
        greedy acceptance advancing per-slot fills — the
        ``make_speculative_generator`` round body (same acceptance/
        emission/eos invariants; a desync there breaks token identity)
        restructured for the resident slot batch. A ``system_prefix``
        arrives PREPENDED to every prompt (the shim), so both prefills
        cover it like any other tokens; no prefix cache in this mode
        (refused at construction).
        """
        import functools

        import jax
        import jax.numpy as jnp

        from unionml_tpu.models.llama import init_cache

        cfg, dcfg = self.cfg, self.draft.config
        L, B, k = self.cache_len, self.slots, self.speculate_k
        module, draft, sample = self.module, self.draft, self._sample
        eos_id, pad_id = self.eos_id, self.pad_id
        R = self.chunk_steps

        def init_state():
            return {
                "cache": init_cache(cfg, B, L),
                "d_cache": init_cache(dcfg, B, L),
                "kv_mask": jnp.zeros((B, L), bool),
                "fill": jnp.zeros((B,), jnp.int32),
                "last_tok": jnp.zeros((B,), jnp.int32),
                "done": jnp.ones((B,), bool),
            }

        self._init_state = jax.jit(init_state)

        def finish_prefill(params, state, fresh, slot, toks, start, true_len,
                           key, *, target_kwargs=None, draft_kwargs=None):
            """Prefill tail for BOTH caches: run the (right-padded)
            bucket/final-chunk through target and draft, sample the first
            token from the target's last real position, splice both
            filled caches into ``slot``."""
            fresh_t, fresh_d = fresh
            bucket = fresh_t[0][0].shape[1]
            c = toks.shape[1]
            kv_mask = (jnp.arange(bucket) < true_len)[None, :]
            pos = start + jnp.arange(c)[None, :]
            logits, filled_t = module.apply(
                {"params": params["target"]}, toks, positions=pos,
                cache=fresh_t, cache_index=start, kv_mask=kv_mask,
                logit_index=jnp.reshape(true_len - 1 - start, (1,)),
                **(target_kwargs or {}),
            )
            # draft prefill logits are never read: DCE'd stub head
            _, filled_d = draft.apply(
                {"params": params["draft"]}, toks, positions=pos,
                cache=fresh_d, cache_index=start, kv_mask=kv_mask,
                logit_index=jnp.zeros((1,), jnp.int32),
                **(draft_kwargs or {}),
            )
            first = sample(logits[:, 0], key)[0]
            cache = _splice_rows(state["cache"], filled_t, slot, 0)
            d_cache = _splice_rows(state["d_cache"], filled_d, slot, 0)
            row_mask = jnp.arange(L) < true_len
            return {
                "cache": cache,
                "d_cache": d_cache,
                "kv_mask": state["kv_mask"].at[slot].set(row_mask),
                "fill": state["fill"].at[slot].set(true_len),
                "last_tok": state["last_tok"].at[slot].set(first),
                "done": state["done"].at[slot].set(False),
            }, first

        # every monolithic admission is a full prefill (any system
        # prefix is part of the prompt) — each model honors its OWN
        # prefill_impl (target and draft configs may differ)
        _t_full = {"full_prefill": True} if cfg.prefill_impl == "flash" else {}
        _d_full = {"full_prefill": True} if dcfg.prefill_impl == "flash" else {}

        def prefill(params, state, slot, tokens, true_len, key):
            fresh = (
                init_cache(cfg, 1, tokens.shape[0]),
                init_cache(dcfg, 1, tokens.shape[0]),
            )
            return finish_prefill(
                params, state, fresh, slot, tokens[None], jnp.int32(0),
                true_len, key, target_kwargs=_t_full, draft_kwargs=_d_full,
            )

        self._prefill = jax.jit(prefill, donate_argnums=(1,))

        @functools.partial(jax.jit, static_argnames=("bucket",))
        def init_fresh(*, bucket):
            return (init_cache(cfg, 1, bucket), init_cache(dcfg, 1, bucket))

        self._init_fresh = init_fresh

        def prefill_step(params, fresh, toks, start):
            fresh_t, fresh_d = fresh
            lf = fresh_t[0][0].shape[1]
            c = toks.shape[1]
            kv_mask = (jnp.arange(lf) < start + c)[None, :]
            pos = start + jnp.arange(c)[None, :]
            _, fresh_t = module.apply(
                {"params": params["target"]}, toks, positions=pos,
                cache=fresh_t, cache_index=start, kv_mask=kv_mask,
                logit_index=jnp.zeros((1,), jnp.int32),
            )
            _, fresh_d = draft.apply(
                {"params": params["draft"]}, toks, positions=pos,
                cache=fresh_d, cache_index=start, kv_mask=kv_mask,
                logit_index=jnp.zeros((1,), jnp.int32),
            )
            return fresh_t, fresh_d

        self._prefill_step = jax.jit(prefill_step, donate_argnums=(1,))
        self._prefill_final = jax.jit(finish_prefill, donate_argnums=(1,))

        def spec_chunk(params, state, active, keys):
            """``chunk_steps`` speculative rounds in one scan. Returns
            per-round ``(emit [R, B, k+1], n_emit [R, B], accepted
            [R, B])`` — the host credits each slot ``n_emit`` tokens per
            round (eos-truncated device-side, budget-truncated host-side
            like the plain path)."""
            arange_l = jnp.arange(L)[None, :]
            rows = jnp.arange(B)

            def round_body(state, _):
                live = active & ~state["done"]
                fill0 = state["fill"]

                # draft proposes k tokens over k+1 steps (the extra step
                # consumes proposal k so a fully-accepted round leaves no
                # draft-cache hole — the make_speculative_generator rule)
                def dstep(c, _):
                    d_cache, tok, f = c
                    vis = state["kv_mask"] | (
                        (arange_l >= fill0[:, None])
                        & (arange_l <= f[:, None])
                        & live[:, None]
                    )
                    logits, d_cache = draft.apply(
                        {"params": params["draft"]}, tok[:, None],
                        cache=d_cache, cache_index=f, kv_mask=vis,
                    )
                    nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
                    return (d_cache, nxt, f + 1), nxt

                (d_cache, _, _), props = jax.lax.scan(
                    dstep, (state["d_cache"], state["last_tok"], fill0),
                    None, length=k + 1,
                )
                props = props.transpose(1, 0)[:, :k]          # [B, k]

                # ONE shared multi-token verify forward for every slot
                verify_in = jnp.concatenate(
                    [state["last_tok"][:, None], props], axis=1
                )
                vis_v = state["kv_mask"] | (
                    (arange_l >= fill0[:, None])
                    & (arange_l <= (fill0 + k)[:, None])
                    & live[:, None]
                )
                v_logits, cache = module.apply(
                    {"params": params["target"]}, verify_in,
                    cache=state["cache"], cache_index=fill0, kv_mask=vis_v,
                )
                from unionml_tpu.models.speculative import greedy_acceptance

                greedy = jnp.argmax(v_logits, -1).astype(jnp.int32)
                accepted, correction, emit = greedy_acceptance(props, greedy)
                n_emit = jnp.where(live, accepted + 1, 0)
                done = state["done"]
                if eos_id is not None:
                    pos_idx = jnp.arange(k + 1)[None, :]
                    eos_hit = (emit == eos_id) & (pos_idx < n_emit[:, None])
                    any_eos = eos_hit.any(axis=1)
                    first_eos = jnp.argmax(eos_hit, axis=1)
                    n_emit = jnp.where(
                        any_eos, jnp.minimum(n_emit, first_eos + 1), n_emit
                    )
                    done = done | (live & any_eos)
                # rows consumed = accepted + 1 (eos shrinks EMISSION, not
                # the cache rows written — done stops later rounds)
                advance = jnp.where(live, accepted + 1, 0)
                new_fill = fill0 + advance
                # freeze before the end: the next round writes k+1 rows
                done = done | (live & (new_fill + k + 1 >= L))
                new_kv = state["kv_mask"] | (
                    (arange_l >= fill0[:, None])
                    & (arange_l < new_fill[:, None])
                )
                new_last = jnp.where(live, correction, state["last_tok"])
                out = (
                    jnp.where(live[:, None], emit, pad_id),
                    n_emit.astype(jnp.int32),
                    jnp.where(live, accepted, 0).astype(jnp.int32),
                )
                return {
                    "cache": cache,
                    "d_cache": d_cache,
                    "kv_mask": new_kv,
                    "fill": new_fill,
                    "last_tok": new_last,
                    "done": done,
                }, out

            state, outs = jax.lax.scan(round_body, state, None, length=R)
            return state, outs

        self._decode_chunk = jax.jit(spec_chunk, donate_argnums=(1,))

    # ------------------------------------------------------------------ #
    # public API
    # ------------------------------------------------------------------ #

    def generate(
        self,
        params,
        prompts: Sequence[Sequence[int]],
        *,
        max_new_tokens: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
    ) -> list:
        """Generate for a list of token-id prompts; blocks until all done.

        Compatible with the ``make_lm_predictor`` row-lists contract:
        returns one token list per prompt. ``params`` binds on first call
        (pass serving-ready weights — cast/quantized).

        ``deadline_ms`` (or an ambient :func:`~unionml_tpu.serving
        .faults.deadline_scope` — how ``X-Deadline-Ms`` reaches here
        through the transports) bounds each request's total latency:
        still-queued requests whose deadline expires are shed at
        dequeue with :class:`~unionml_tpu.serving.faults
        .DeadlineExceeded`, before they consume prefill.

        ``tenant`` (or the ambient :func:`~unionml_tpu.serving.usage
        .tenant_scope` the transports open from ``X-Tenant-ID``) names
        who this call's resource vector is billed to when the engine
        runs a usage ledger; defaults to ``anonymous``.

        ``priority`` (or the ambient :func:`~unionml_tpu.serving
        .scheduler.priority_scope` the transports open from
        ``X-Priority``) sets the scheduling class — ``high`` /
        ``normal`` / ``low`` — the waiting room orders admissions by
        and the preemptive scheduler arbitrates pool pressure with
        (docs/robustness.md "Preemption & fairness").
        """
        self.bind(params)
        tenant = (
            validate_tenant(tenant) if tenant is not None
            else current_tenant()
        )
        priority = (
            validate_priority(priority) if priority is not None
            else current_priority()
        )
        if max_new_tokens is None:
            # the ambient per-request cap the transports open from the
            # /predict payload's max_new_tokens field (the deadline-
            # scope pattern) — how a caller's cap survives the router
            # hop without threading a kwarg through every predictor
            max_new_tokens = current_token_cap()
        n = max_new_tokens if max_new_tokens is not None else self.max_new_tokens
        if not 1 <= n <= self.max_new_tokens:
            raise ValueError(
                f"max_new_tokens {n} outside [1, {self.max_new_tokens}] "
                "(raise the engine's max_new_tokens)"
            )
        if deadline_ms is None:
            deadline_ms = current_deadline_ms()
        # validate EVERY prompt before creating any request or trace
        # rid, so a bad later prompt cannot leak earlier ones' state
        rows = [self._canonical_row(p) for p in prompts]
        reqs = []
        for row in rows:
            req = _Request(
                prompt=row, max_new_tokens=n, tenant=tenant,
                priority=priority,
            )
            if deadline_ms is not None:
                req.deadline = req.submitted + deadline_ms / 1e3
            req.rid = self._tracer.new_request("generate")
            reqs.append(req)
        try:
            self._gated_submit(reqs)
        except BaseException:
            # rejected before enqueue: close the trace timelines or the
            # recorder leaks one live request per shed submission —
            # precisely under the sustained overload shedding exists for
            for req in reqs:
                self._tracer.finish_request(req.rid)
            raise
        out = []
        for req in reqs:
            if not req.event.wait(self.submit_timeout):
                # abandon the whole call: queued siblings are dropped at
                # admission and in-slot ones retired at the next harvest,
                # so orphans stop burning device time and slots
                self._m_timeouts.inc()
                for r in reqs:
                    r.abandoned = True
                raise TimeoutError("decode engine did not finish in time")
            if req.error is not None:
                raise req.error
            out.append(list(req.tokens))
        return out

    def generate_stream(
        self,
        params,
        prompt: Sequence[int],
        *,
        max_new_tokens: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
    ):
        """Yield token chunks for ONE prompt as the engine harvests them.

        The streaming surface behind ``POST /predict/stream``: the first
        chunk arrives after prefill (one token — the TTFT event), then
        one chunk per harvested decode chunk (``chunk_steps`` tokens at
        the engine's natural emission granularity). Concatenating the
        chunks yields exactly ``generate(params, [prompt])[0]`` (tested
        in tests/unit/test_engine.py). Raises the engine's error, or
        ``TimeoutError`` when no chunk lands within ``submit_timeout``.
        """
        self.bind(params)
        tenant = (
            validate_tenant(tenant) if tenant is not None
            else current_tenant()
        )
        priority = (
            validate_priority(priority) if priority is not None
            else current_priority()
        )
        if max_new_tokens is None:
            max_new_tokens = current_token_cap()  # payload-field cap
        n = max_new_tokens if max_new_tokens is not None else self.max_new_tokens
        if not 1 <= n <= self.max_new_tokens:
            raise ValueError(
                f"max_new_tokens {n} outside [1, {self.max_new_tokens}] "
                "(raise the engine's max_new_tokens)"
            )
        if deadline_ms is None:
            deadline_ms = current_deadline_ms()
        row = self._canonical_row(prompt)
        req = _Request(
            prompt=row, max_new_tokens=n, stream=queue.Queue(),
            tenant=tenant, priority=priority,
        )
        if deadline_ms is not None:
            req.deadline = req.submitted + deadline_ms / 1e3
        req.rid = self._tracer.new_request("stream")
        try:
            self._gated_submit([req])
        except BaseException:
            self._tracer.finish_request(req.rid)  # no leak on rejection
            raise
        try:
            while True:
                try:
                    chunk = req.stream.get(timeout=self.submit_timeout)
                except queue.Empty:
                    self._m_timeouts.inc()
                    raise TimeoutError(
                        "decode engine produced no chunk in time"
                    ) from None
                if chunk is None:
                    if req.error is not None:
                        raise req.error
                    return
                yield chunk
        finally:
            # consumer stopped early (client disconnect → GeneratorExit,
            # timeout, error): mark abandoned so the slot is retired at
            # the next harvest instead of decoding to max_new_tokens for
            # a dead request
            if not req.event.is_set():
                req.abandoned = True

    def _canonical_row(self, prompt) -> np.ndarray:
        """The engine's canonical prompt row: left-truncated to the
        user budget, system prefix prepended — ONE home shared by the
        generate paths and the KV export, so a disaggregated prefill
        engine and its decode peer (configured identically) key the
        same bytes under the same tokens."""
        row = np.asarray(prompt, dtype=np.int32).ravel()
        if row.size == 0:
            raise ValueError("empty prompt")
        row = row[-self._user_max:]
        if self._prefix_tokens is not None:
            row = np.concatenate([self._prefix_tokens, row])
        return row

    def prefill_export(
        self,
        params,
        prompt: Sequence[int],
        *,
        deadline_ms: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
    ) -> dict:
        """Prefill-only admission — the disaggregated serving prefill
        leg (docs/serving.md "Disaggregated serving", DistServe/
        Splitwise lineage): run the prompt's (possibly chunked)
        prefill through the NORMAL admission machinery, let the
        harvest finalize the prompt's full KV blocks into the host
        prefix-cache store (the same extract/insert path every
        admission takes — pointer handoff, no extra copies), and
        return a KV handle instead of streaming:

        ``{"tokens": [first_token], "prompt": [...canonical row...],
        "cached_tokens": N, "lease": PrefixLease, "rid": ...}``

        The first sampled token gives the router its TTFT emission;
        ``lease`` pins the exported path against LRU eviction until
        the decode leg has spliced (release it exactly once — it is
        idempotent, so the router's finally is safe under retries);
        ``cached_tokens`` is how much of the prompt a decode engine
        sharing this host store will splice instead of recomputing.
        Blocks that could not be stored (byte budget) simply shrink
        the match — the decode leg recomputes the difference, so the
        handoff degrades, never errors. Billing is exactly a normal
        1-token request's: the prefill window goes to the admitting
        tenant under this engine's ``phase`` label."""
        if self.prefix_cache is None:
            raise ValueError(
                "prefill_export needs a prefix cache — the harvested "
                "KV blocks land in its host block store for the decode "
                "leg to splice; construct the engine with "
                "prefix_cache=True (or a shared RadixPrefixCache)"
            )
        self.bind(params)
        tenant = (
            validate_tenant(tenant) if tenant is not None
            else current_tenant()
        )
        priority = (
            validate_priority(priority) if priority is not None
            else current_priority()
        )
        if deadline_ms is None:
            deadline_ms = current_deadline_ms()
        row = self._canonical_row(prompt)
        req = _Request(
            prompt=row, max_new_tokens=1, tenant=tenant, priority=priority,
        )
        req._kv_event = threading.Event()
        if deadline_ms is not None:
            req.deadline = req.submitted + deadline_ms / 1e3
        req.rid = self._tracer.new_request("prefill")
        try:
            self._gated_submit([req])
        except BaseException:
            self._tracer.finish_request(req.rid)  # no leak on rejection
            raise
        if not req.event.wait(self.submit_timeout):
            self._m_timeouts.inc()
            req.abandoned = True
            raise TimeoutError("prefill did not finish in time")
        if req.error is not None:
            raise req.error
        # the request finished at its prefill harvest; the insert
        # entry carrying its KV blocks into the host store is FIFO
        # right behind it — wait for the lease release that marks the
        # insert processed, so the handle's lease actually covers the
        # just-exported path (a timeout here degrades to a shorter
        # match, never an error)
        req._kv_event.wait(self.submit_timeout)
        lease = self.prefix_cache.lease(row)
        return {
            "tokens": list(req.tokens),
            "prompt": [int(t) for t in row],
            "cached_tokens": int(lease.n_tokens),
            "lease": lease,
            "rid": req.rid,
            "engine": self.instance,
        }

    def kv_export(
        self, prompt: Sequence[int], *, wait_s: float = 0.25,
    ) -> List[dict]:
        """Export the host prefix-cache block entries covering
        ``prompt`` — the donor half of the CROSS-PROCESS KV handoff
        (the ``POST /debug/kv/export`` handler; same-host pools share
        the store object and never need this). ``wait_s`` bounds a
        short poll for in-flight inserts: the caller typically asks
        right after its prefill response, while the harvest pipeline
        may still be attaching the final blocks — whatever is covered
        when the budget expires is exported (the decode side
        recomputes the rest: degrade, never error)."""
        cache = self.prefix_cache
        if cache is None:
            raise ValueError(
                "no prefix cache on this engine — KV export needs the "
                "host block store; construct with prefix_cache=True"
            )
        row = self._canonical_row(prompt)
        target = (len(row) // cache.block_size) * cache.block_size
        deadline = time.monotonic() + max(0.0, wait_s)
        while cache.peek(row) < target and time.monotonic() < deadline:
            time.sleep(0.005)
        return cache.export_request(row)

    def kv_import(self, entries: Sequence[dict]) -> int:
        """Attach a donor's exported block entries to this engine's
        host prefix-cache store (the ``POST /debug/kv/import``
        handler / the router's cross-store transfer): each entry
        rides the normal insert budget/eviction machinery; returns
        blocks newly attached."""
        cache = self.prefix_cache
        if cache is None:
            raise ValueError(
                "no prefix cache on this engine — KV import needs the "
                "host block store; construct with prefix_cache=True"
            )
        return int(cache.import_blocks(entries))

    def bind(self, params):
        """Set (or swap) the served weights; state allocates lazily.

        Swapping while requests are in flight would mix weights within a
        decode (later chunks of an in-flight request would run under the
        new tree against a KV cache built with the old one) — refuse
        instead of corrupting silently.
        """
        if params is self._params:
            return
        if self.draft is not None:
            from collections.abc import Mapping

            if not (
                isinstance(params, Mapping)
                and "target" in params
                and "draft" in params
            ):
                raise ValueError(
                    'a speculative engine binds a mapping {"target": '
                    'params, "draft": params} (the '
                    "make_speculative_predictor artifact contract)"
                )
        with self._lock:
            busy = (
                any(r is not None for r in self._occupant)
                or self._admitting > 0
                or not self._room.empty()
                # a preempted stream in evict→resume limbo lives only
                # in the in-flight pipeline: its host KV belongs to
                # the CURRENT weights, so a swap must wait for it
                or not self._inflight.empty()
            )
            if self._params is not None and busy:
                raise RuntimeError(
                    "cannot swap engine params while requests are in "
                    "flight — drain the engine (or create a new one) first"
                )
            if self._params is not None and self.prefix_cache is not None:
                # stored KV blocks belong to the OLD weights; splicing
                # them under the new tree would corrupt silently (pin
                # registrations survive — the prefix re-pins on
                # reinsert). The device-resident splice memo goes with
                # them.
                self.prefix_cache.clear()
                self._dev_splice.clear()
            self._params = params

    def warmup(self, params) -> int:
        """Pre-compile the engine executables: per bucket, the cold
        prefill, and — with a prefix cache — that bucket's cached
        admission path too (splice + ``[1, block]`` finish via a
        full-hit pass, the ``[1, block]`` lead chunk via a partial-hit
        pass where the bucket has room), plus the decode chunk and the
        extract programs. A live request must never pay a serve-time
        XLA compile just because it HIT the cache. Returns the number
        of cold-path executables; the cache is left empty."""
        self.bind(params)
        # 2 tokens, not 1: a 1-token request completes at prefill and
        # would never compile the decode chunk
        n = min(2, self.max_new_tokens)
        for b in self.buckets:
            if self.prefix_cache is not None:
                # each bucket must MISS first so its cold program
                # compiles (every admission inserts, and the warmup
                # prompts share prefixes across buckets)
                self.prefix_cache.clear()
            ones = np.ones(b - self.prefix_len, np.int32)
            self.generate(params, [ones], max_new_tokens=n)
            if self.prefix_cache is not None:
                blk = self.prefix_cache.block_size
                # full hit: splices + the [1, block] finish program
                self.generate(params, [ones], max_new_tokens=n)
                if b >= 3 * blk:
                    # partial hit (>= 1 matched block, >= 2 uncovered):
                    # compiles the [1, block] lead-chunk program
                    part = ones.copy()
                    part[-2 * blk:] = 2
                    self.generate(params, [part], max_new_tokens=n)
        if self.prefix_cache is not None:
            self.prefix_cache.clear()
        return len(self.buckets) + 1

    def stats(self) -> dict:
        """Serving observability: request timing splits + slot occupancy.

        A thin view over this instance's telemetry-registry series (the
        same numbers ``GET /metrics`` exposes) keeping the historical
        key shape; percentiles come from the histograms' exact sample
        windows, not bucket interpolation."""
        steps = int(self._m_steps.value)
        occupied = int(self._m_occupied.value)
        out = {
            "engine": "continuous",
            "phase": self.phase,
            "slots": self.slots,
            "chunk_steps": self.chunk_steps,
            "pipeline_depth": self.pipeline_depth,
            "completed_requests": int(self._m_requests.value),
            "decode_steps": steps,
            "slot_occupancy": round(occupied / max(1, steps * self.slots), 3),
        }
        if self.draft is not None:
            spec_rounds = int(self._m_spec_rounds.value)
            spec_accepted = int(self._m_spec_accepted.value)
            out["speculative"] = {
                "k": self.speculate_k,
                "rounds": spec_rounds,
                "accepted_draft_tokens": spec_accepted,
                # fraction of proposed draft tokens the target accepted
                "acceptance_rate": round(
                    spec_accepted / max(1, spec_rounds * self.speculate_k), 3
                ),
            }
        if self.prefix_cache is not None:
            out["prefix_cache"] = self.prefix_cache.stats()
        if self.kv_pool is not None:
            out["kv_pool"] = self.kv_pool.stats()
        if self._usage is not None:
            # the compact per-tenant view (GET /debug/usage has the
            # full per-tenant resource vectors)
            out["usage"] = self._usage.stats()
        if self._programs is not None:
            # hardware truth per compiled program: flops/bytes, compile
            # counts, MFU/roofline ratios (docs/observability.md)
            out["programs"] = self._programs.stats()
        out["robustness"] = {
            "queue_depth": self._room.qsize(),
            "rejected": {
                reason: int(c.value)
                for reason, c in self._m_rejected.items()
            },
            "deadline_shed": int(self._m_deadline_shed.value),
            "recoveries": int(self._m_recoveries.value),
            "breaker_open": self.breaker_open,
            "draining": self._draining,
        }
        # the preemptive scheduler's view: per-class waiting depths,
        # parked pool-exhausted admissions, evictions performed
        out["scheduler"] = self._sched.stats()
        for name, h in (
            ("queue_wait_ms", self._h_queue),
            ("prefill_ms", self._h_prefill),
            ("decode_ms", self._h_decode),
            ("ttft_ms", self._h_ttft),
        ):
            summary = h.summary()
            if summary:
                out[name] = summary
        # decode-lane-pure inter-token latency (the perf plane's
        # chunk-spacing histograms merged across priority classes):
        # unlike decode_ms, no harvest/admission gaps are lumped in
        itl = self._itl_summary()
        if itl:
            out["itl_ms"] = itl
            out["itl_mean_ms"] = itl["mean"]
            out["itl_p99_ms"] = itl["p99"]
        if self._perf is not None:
            out["goodput"] = self._perf.report()
        return out

    def _itl_summary(self) -> dict:
        """Exact percentile summary of the ITL histograms' retained
        windows merged across this engine's priority children
        (``{}`` when the plane is off or nothing decoded yet)."""
        samples: List[float] = []
        for child in self._h_itl.values():
            samples.extend(child.samples())
        if not samples:
            return {}
        return telemetry.percentile_summary(samples)

    def goodput_report(self) -> dict:
        """The ``GET /debug/goodput`` body for this engine: the perf
        plane's ring classification + ratios + watchdog advisory,
        with the ITL/TTFT summaries and — when introspection is on —
        the per-program MFU/roofline view, so achieved tokens/s and
        hardware utilization read off one dashboard. Raises
        ``ValueError`` when the plane is off (transports map it to
        422)."""
        if self._perf is None:
            raise ValueError(
                "serving perf plane is off — construct the engine "
                "with perf=True (the default while introspect=True)"
            )
        out = self._perf.report()
        itl = self._itl_summary()
        if itl:
            out["itl_ms"] = itl
        ttft = self._h_ttft.summary()
        if ttft:
            out["ttft_ms"] = ttft
        if self._programs is not None:
            progs = self._programs.stats()
            out["programs"] = {
                name: {
                    "mfu": p["mfu"],
                    "hbm_utilization": p.get("hbm_utilization"),
                    "achieved_flops_per_s": p.get("achieved_flops_per_s"),
                }
                for name, p in progs.items()
                if isinstance(p, dict) and "mfu" in p
            }
        return out

    def reset_stats(self) -> None:
        """Zero this instance's observability series (benchmarks call
        this between scenarios so each phase's /stats describes only
        that phase); scrapers see the resets as counter restarts."""
        for m in (
            self._m_requests, self._m_errors, self._m_abandoned,
            self._m_timeouts, self._m_steps, self._m_chunks,
            self._m_occupied, self._m_spec_rounds, self._m_spec_accepted,
            self._m_deadline_shed, self._m_recoveries,
            *self._m_rejected.values(),
            self._h_queue, self._h_prefill, self._h_decode, self._h_ttft,
            self._h_dispatch, self._h_harvest, self._h_drain,
            *self._h_itl.values(),
        ):
            m.reset()
        if self._perf is not None:
            self._perf.reset()
        if self.prefix_cache is not None:
            self.prefix_cache.reset_stats()
        if self.kv_pool is not None:
            self.kv_pool.reset_stats()
        if self._usage is not None:
            self._usage.reset_stats()
        if self._programs is not None:
            self._programs.reset()
        self._sched.reset_stats()

    def close(self):
        self._stop.set()
        self._worker.join(timeout=5.0)
        self._harvester.join(timeout=5.0)
        with self._lock:
            adm, self._admission = self._admission, None
        if adm is not None:
            self._drop_admission(adm.req, RuntimeError("decode engine closed"))
        while True:
            parked = self._room.take_parked()
            if parked is None:
                break
            self._drop_admission(parked, RuntimeError("decode engine closed"))
        # drain the in-flight pipeline the harvester no longer owns:
        # stranded insert entries still hold lease refcounts — leaking
        # them would pin blocks in a user-supplied cache forever — and
        # a stranded preempt entry holds a request in evict→resume
        # limbo that no queue or slot structure can see
        while True:
            try:
                entry = self._inflight.get_nowait()
            except queue.Empty:
                break
            if entry[0] == "insert":
                self._release_lease(entry[2])
            elif entry[0] == "preempt":
                self._fail_orphan(
                    entry[2], RuntimeError("decode engine closed")
                )
        for req in self._room.pop_all():
            req.error = RuntimeError("decode engine closed")
            self._release_lease(req)  # a resumed-queued stream's pin
            self._tracer.finish_request(req.rid)
            req.event.set()
            req.finish_stream()
        for req in self._occupant:
            if req is not None:
                req.error = RuntimeError("decode engine closed")
                self._tracer.finish_request(req.rid)
                self._release_lease(req)
                req.event.set()
                req.finish_stream()
        self._occupant = [None] * self.slots
        self._m_slots_busy.set(0)

    # ------------------------------------------------------------------ #
    # engine loop
    # ------------------------------------------------------------------ #

    def _bucket_for(self, length: int) -> int:
        for b in self.buckets:
            if length <= b:
                return b
        return self.buckets[-1]

    def _next_key(self, num: int = 1):
        self._key, *subs = self._jax.random.split(self._key, num + 1)
        return subs

    def _admission_preamble(self, req: _Request):
        """The shared start of every admission (monolithic and chunked —
        ONE home so timing/padding policy cannot desync): pick the free
        slot, stamp queue-wait, right-pad the prompt to its bucket."""
        with self._lock:
            slot = self._occupant.index(None)
        t0 = time.perf_counter()
        if req._preempted_at:
            # a resumed stream: the original queue wait already landed
            # in the histogram/span — record the evict→re-admit gap as
            # its own span instead of corrupting the queue timing
            self._tracer.record_span(
                req.rid, f"resume-wait[{req._preempts - 1}]",
                req._preempted_at, t0,
            )
        else:
            req.queue_wait_ms = (t0 - req.submitted) * 1e3
            self._tracer.record_span(req.rid, "queue", req.submitted, t0)
        req._dispatch_t = t0
        bucket = self._bucket_for(len(req.prompt))
        padded = np.full(bucket, self.pad_id, np.int32)
        padded[: len(req.prompt)] = req.prompt
        return slot, bucket, padded

    def _admit(self, req: _Request):
        """Dispatch ``req``'s prefill into a free slot WITHOUT blocking on
        the first token (its readback is harvested later, in dispatch
        order). Dispatcher thread only; occupancy mutates under the lock."""
        import jax.numpy as jnp

        slot, _bucket, padded = self._admission_preamble(req)
        (key,) = self._next_key()
        with self._lock:
            ep0 = self._epoch
            st = self._state
            ids = (
                self._take_covered_locked(req, slot, _bucket)
                if self.paged else None
            )
        if st is None:
            st = self._init_state()
        if self.paged:
            new_state, first = self._prefill(
                self._params, st, jnp.int32(slot), jnp.asarray(ids),
                jnp.asarray(padded), jnp.int32(len(req.prompt)), key,
            )
        else:
            new_state, first = self._prefill(
                self._params, st, jnp.int32(slot), jnp.asarray(padded),
                jnp.int32(len(req.prompt)), key,
            )
        _start_host_copy(first)
        if self._usage is not None:
            # the monolithic prefill's cost-analysis FLOPs, accumulated
            # for attribution at this request's prefill harvest
            req._attr_flops += self._program_cost(
                "engine.prefill", tuple(padded.shape)
            )
        with self._lock:
            if self._epoch != ep0:
                # _recover ran (harvester thread) while this prefill was
                # in flight: new_state derives from the invalidated
                # resident buffers — DISCARD it (self._state stays the
                # recovery's None, so the next admission rebuilds) and
                # fail this request with the poisoned batch (the raise
                # lands in _start_admission's error path).
                raise RuntimeError(
                    "engine recovered while this admission's prefill "
                    "was in flight; the request failed with the "
                    "poisoned batch"
                )
            self._state = new_state
            self._occupant[slot] = req
            self._slot_gen[slot] += 1
            # resumed streams already hold harvested tokens; dispatch
            # accounting continues from them (fresh admissions: 0 + 1)
            req._expected = len(req.tokens) + 1
            self._m_slots_busy.set(self._slots_in_use_locked())
        # admission segment: dispatch start → prefill program enqueued
        # (host-side admission machinery; the device part of prefill
        # lands in prefill_ms at harvest)
        req.admission_ms = (time.perf_counter() - req._dispatch_t) * 1e3
        self._flight_rec(
            "prefill", rid=req.rid, tenant=req.tenant, slot=slot,
            bucket=_bucket, tokens=req._prefilled_tokens,
            cached_tokens=req._saved_tokens,
        )
        self._inflight.put(("prefill", ep0, slot, req, first))
        self._schedule_insert(req, slot, ep0)

    def _device_splice_rows(self, blocks):
        """Device-resident rows for one splice unit (a tuple of cached
        host block trees), LRU-memoized on the blocks' object identity:
        a hot prefix — the pinned ``system_prefix`` above all — uploads
        host→device ONCE, then every later admission splices the
        resident copy. Each entry keeps the host tuples alive, so an
        ``id()`` key can never be recycled while its entry lives.
        Dispatcher thread only."""
        import jax.numpy as jnp

        key = tuple(id(b) for b in blocks)
        hit = self._dev_splice.get(key)
        if hit is not None:
            self._dev_splice.move_to_end(key)
            return hit[1]
        host = blocks[0] if len(blocks) == 1 else _concat_rows(blocks)
        dev = self._jax.tree_util.tree_map(jnp.asarray, host)
        self._dev_splice[key] = (blocks, dev)
        while len(self._dev_splice) > self._dev_splice_cap:
            self._dev_splice.popitem(last=False)
        return dev

    def _schedule_insert(self, req: _Request, slot: int, epoch: int) -> None:
        """Dispatcher, right after a prefill dispatch: extract the
        slot's leading resident rows in ONE compiled dispatch, kick the
        async device→host copy, and queue the tree insert behind the
        in-flight readbacks — the harvester materializes the bytes once
        they are already local and splits them into blocks, so neither
        thread blocks on the transfer. Fully-matched prompts skip the
        extraction; the entry always carries the request so its lease is
        released only after the insert could build on live ancestors."""
        import jax.numpy as jnp

        cache = self.prefix_cache
        if cache is None:
            return
        nb = len(req.prompt) // cache.block_size
        first_new = min(req._matched_blocks, nb)
        st = self._state  # one read: _recover may null it concurrently
        if first_new >= nb or st is None:
            rows = None  # nothing new to store — release-only entry
        elif self.paged:
            # gather the slot's blocks BY TABLE ENTRY (one compiled
            # dispatch per bucket; uncovered tail entries gather the
            # trash block and are never inserted) — the paged form of
            # the contiguous row-window extract
            blk = self._kv_block_size
            with self._lock:
                ids = self._table[
                    slot, : self._bucket_for(len(req.prompt)) // blk
                ].copy()
            rows = self._extract_blocks(st["pool"], jnp.asarray(ids))
            for layer in rows:
                for buf in layer:
                    _start_host_copy(buf)
        else:
            rows = self._extract_rows(
                st["cache"], jnp.int32(slot),
                n=self._bucket_for(len(req.prompt)),
            )
            for layer in rows:
                for buf in layer:
                    _start_host_copy(buf)
        self._inflight.put(("insert", epoch, req, first_new, rows))

    def _release_lease(self, req: _Request) -> None:
        """Unpin the request's matched cache blocks AND any resume pin
        (idempotent; error paths and the insert path may both get
        here). Entry ordering makes releasing both safe: an insert
        entry for admission N always processes before the preempt
        entry that would set a new resume lease."""
        lease, req._lease = req._lease, None
        if lease is not None:
            lease.release()
        self._release_resume_lease(req)
        if req._kv_event is not None:
            # prefill_export waits on this: the normal insert entry
            # lands here after attaching the request's KV blocks, and
            # every failure path lands here too — the export waiter
            # wakes either way (checking req.error), never hangs
            req._kv_event.set()

    def _release_resume_lease(self, req: _Request) -> None:
        """Drop the pin holding a preempted stream's evicted KV blocks
        in the host cache — once the resume admission has taken its
        own match lease over the same path, or on any terminal path
        (idempotent)."""
        lease, req._resume_lease = req._resume_lease, None
        if lease is not None:
            lease.release()

    def _fail_orphan(self, req: _Request, exc: BaseException) -> None:
        """Fail a request stranded in evict→resume limbo: between its
        preemption and its requeue it lives ONLY in the in-flight
        pipeline, so recovery and close cannot reach it through any
        occupant/queue structure — the preempt entry's failure arms
        must finish it or its waiter hangs forever. Its pool blocks
        were already released at eviction (generation-guarded against
        a concurrent pool reset)."""
        with self._lock:
            if req.event.is_set():
                return
            req.error = exc
        self._release_lease(req)
        self._m_errors.inc()
        if self._usage is not None:
            self._usage.record_drop(req.tenant, "error")
        self._flight_rec(
            "drop", rid=req.rid, tenant=req.tenant,
            cause=f"error:{type(exc).__name__}",
        )
        self._tracer.finish_request(req.rid)
        req.event.set()
        req.finish_stream()

    # ------------------------------------------------------------------ #
    # usage metering helpers (no-ops when usage=None)
    # ------------------------------------------------------------------ #

    def _program_cost(self, key: str, sig=None) -> float:
        """Cost-analysis FLOPs of one dispatch of a tracked program
        (0 when introspection is off or the program never compiled) —
        the per-dispatch numerator the ledger splits across tenants."""
        if self._programs is None:
            return 0.0
        return self._programs.cost(key, sig)[0]

    def _usage_kv_release(self, req: _Request) -> None:
        """Integrate the request's pool-block hold times into its
        tenant's KV block-seconds (idempotent: the stamp list drains).
        Called on every path that gives the blocks back — retirement,
        mid-admission drop, and recovery — so no hold window is left
        open for an abandoned or poisoned request."""
        if self._usage is None or not req._block_t0:
            req._block_t0 = []
            return
        now = time.monotonic()
        held = sum(now - t0 for t0 in req._block_t0)
        req._block_t0 = []
        self._usage.record_kv_block_seconds(req.tenant, held)

    # ------------------------------------------------------------------ #
    # paged-mode pool bookkeeping (engine lock held for all of these)
    # ------------------------------------------------------------------ #

    def _sweep_deferred_locked(self) -> None:
        """Free deferred block batches whose fence has passed: every
        decode chunk dispatched before the owning slot retired has been
        harvested, so no in-flight program can still write the rows."""
        if not self._deferred_free:
            return
        keep = []
        for fence, ids in self._deferred_free:
            if fence <= self._harvest_seq:
                self.kv_pool.give(ids)
            else:
                keep.append((fence, ids))
        self._deferred_free = keep

    def _take_covered_locked(self, req: _Request, slot: int,
                             bucket: int) -> np.ndarray:
        """Convert the leading ``ceil(true_len / block)`` of the
        request's reservation into concrete pool blocks, install them
        in the slot's table row, and return the scatter id vector
        ([bucket/block] int32, trash-padded) the prefill program
        consumes. The rest of the reservation converts lazily as
        decode fills rows (_grow_tables_locked)."""
        blk = self._kv_block_size
        nbb = bucket // blk
        covered = self.kv_pool.blocks_for_rows(len(req.prompt))
        ids = np.zeros(nbb, np.int32)
        self._table[slot, :] = 0
        t_take = time.monotonic() if self._usage is not None else 0.0
        for j in range(covered):
            bid = self.kv_pool.take()
            req._resv_blocks -= 1
            req._block_ids.append(bid)
            if self._usage is not None:
                req._block_t0.append(t_take)
            ids[j] = bid
            self._table[slot, j] = bid
        self._slot_covered[slot] = covered
        self._slot_rows[slot] = len(req.prompt)
        return ids

    def _grow_tables_locked(self) -> np.ndarray:
        """Grow every live slot's block table to cover the NEXT decode
        chunk's worst-case advance (``chunk_steps`` rows), drawing from
        each request's admission-time reservation — which is why growth
        can never fail — and return the table snapshot the chunk
        dispatch uploads. Rows past a request's reserved budget stay on
        the trash block: only overshoot (post-eos / post-budget device
        writes whose tokens the host discards) ever lands there."""
        used_rows = 0
        for slot, req in enumerate(self._occupant):
            if req is None:
                continue
            target_rows = min(
                self._slot_rows[slot] + self.chunk_steps, req._rows_cap
            )
            want = min(
                self.kv_pool.blocks_for_rows(target_rows),
                self._table_width,
            )
            while self._slot_covered[slot] < want and req._resv_blocks > 0:
                bid = self.kv_pool.take()
                req._resv_blocks -= 1
                req._block_ids.append(bid)
                if self._usage is not None:
                    req._block_t0.append(time.monotonic())
                self._table[slot, self._slot_covered[slot]] = bid
                self._slot_covered[slot] += 1
            used_rows += min(self._slot_rows[slot], req._rows_cap)
        self.kv_pool.note_used_rows(used_rows)
        return self._table.copy()

    def _release_blocks_locked(self, req: _Request,
                               slot: Optional[int] = None) -> None:
        """Retirement-path release: taken blocks go on the DEFERRED
        list fenced at the current dispatch seq (an in-flight chunk
        dispatched before this retirement may still write them — the
        free lands only after its harvest); the untaken reservation
        releases immediately (never in any table)."""
        self._usage_kv_release(req)
        ids, req._block_ids = list(req._block_ids), []
        unreserve, req._resv_blocks = req._resv_blocks, 0
        if slot is not None:
            self._table[slot, :] = 0
            self._slot_covered[slot] = 0
            self._slot_rows[slot] = 0
        if req._pool_gen != self.kv_pool.generation:
            return  # a recovery reset the pool under us: ids are stale
        if ids:
            self._deferred_free.append((self._dispatch_seq, ids))
        if unreserve:
            self.kv_pool.give([], unreserve=unreserve)
        self._sweep_deferred_locked()

    def _drop_blocks_now_locked(self, req: _Request) -> None:
        """Mid-admission release (the slot never became occupied, so
        every chunk dispatched so far carried ``active=False`` for it —
        its writes are trash-routed on device): immediate free."""
        self._usage_kv_release(req)
        ids, req._block_ids = list(req._block_ids), []
        unreserve, req._resv_blocks = req._resv_blocks, 0
        if req._pool_gen != self.kv_pool.generation:
            return  # a recovery reset the pool under us: ids are stale
        if ids or unreserve:
            self.kv_pool.give(ids, unreserve=unreserve)

    def _req_done(self, req: _Request, tok: int) -> bool:
        """The single stop predicate (shared by retirement and the
        harvest loop's chunk-splitting — one home so a future stop
        criterion cannot desync them)."""
        return (
            req.abandoned
            or (self.eos_id is not None and tok == self.eos_id)
            or len(req.tokens) >= req.max_new_tokens
        )

    def _observe_itl(self, req: _Request, now: float, n_tokens: int) -> None:
        """Harvester, lock held, perf plane on: one decode chunk's
        inter-token latency — harvest spacing since the previous
        harvested token batch, divided over this chunk's tokens. An
        unanchored request (anchor 0.0: first batch of a segment, or
        just resumed after preemption) only re-anchors, so neither the
        prefill gap nor the evict→resume gap ever counts as ITL and
        resume segments never double-count."""
        self._perf.note_tokens(n_tokens)
        anchor = req._itl_anchor
        req._itl_anchor = now
        if anchor <= 0.0:
            return
        gap_ms = (now - anchor) * 1e3
        self._h_itl[req.priority].observe(gap_ms / n_tokens)
        req._itl_sum_ms += gap_ms
        req._itl_n += n_tokens

    def _finish_if_done(self, slot: int, tok: int) -> bool:
        """Harvester thread, called with the lock held."""
        req = self._occupant[slot]
        if req is None:
            return True
        done = self._req_done(req, tok)
        if done:
            now = time.perf_counter()
            req.decode_ms = (now - req._prefill_end) * 1e3
            # decode_ms is wall time first-token→retirement, so it
            # includes harvest/queue gaps between chunks; the ITL
            # accumulators (chunk-spacing only, reset across
            # preemption) are the decode-lane-pure view
            itl_mean = req._itl_sum_ms / req._itl_n if req._itl_n else 0.0
            if not req.abandoned:
                # exemplar tagging (perf plane only): a top-bucket
                # observation keeps its rid, so GET /debug/tail can
                # hand the slowest recent requests to /debug/trace
                ex = req.rid if self._perf is not None else None
                self._h_queue.observe(req.queue_wait_ms, exemplar=ex)
                self._h_prefill.observe(req.prefill_ms, exemplar=ex)
                self._h_decode.observe(req.decode_ms, exemplar=ex)
                self._h_ttft.observe(req.ttft_ms, exemplar=ex)
                self._m_requests.inc()
                if self._perf is not None:
                    self._perf.observe_request(req.ttft_ms, itl_mean)
                # a successful completion proves the rebuilt state
                # serves: only CONSECUTIVE rebuild failures accumulate
                # toward the circuit breaker
                self._recovery_times.clear()
            else:
                self._m_abandoned.inc()
            self._occupant[slot] = None
            if self.paged:
                # taken blocks free behind the dispatch fence (chunks
                # already in flight may still write them); the untaken
                # reservation frees now
                self._release_blocks_locked(req, slot)
            self._m_slots_busy.set(self._slots_in_use_locked())
            self._tracer.record_span(req.rid, "harvest", self._harvest_t0, now)
            self._tracer.finish_request(req.rid)
            if self._usage is not None:
                if req.abandoned:
                    self._usage.record_drop(req.tenant, "abandoned")
                else:
                    self._usage.finish_request(
                        req.tenant, queue_ms=req.queue_wait_ms,
                        prefill_tokens=req._prefilled_tokens,
                        cached_tokens=req._saved_tokens,
                        priority=req.priority,
                        phase=self.phase,
                        version=self.model_version,
                    )
            self._flight_rec(
                "finish", rid=req.rid, tenant=req.tenant, slot=slot,
                tokens=len(req.tokens), abandoned=req.abandoned,
                # the per-request ledger split (docs/observability.md
                # "Serving goodput & tail attribution"): queue →
                # admission → prefill → decode segments + the
                # decode-lane-pure ITL rollup
                queue_ms=round(req.queue_wait_ms, 3),
                admission_ms=round(req.admission_ms, 3),
                prefill_ms=round(req.prefill_ms, 3),
                ttft_ms=round(req.ttft_ms, 3),
                decode_ms=round(req.decode_ms, 3),
                itl_mean_ms=round(itl_mean, 3),
                itl_tokens=req._itl_n,
            )
            req.event.set()
            req.finish_stream()
        return done

    def _process_entry(self, entry) -> None:
        """Account one readback's tokens (harvester thread). The blocking
        ``np.asarray`` happened outside the lock; entries arrive in
        dispatch order, so a slot's prefill token always lands before its
        decode tokens and before any reuse of the slot."""
        self._harvest_t0 = time.perf_counter()
        with self._lock:
            cur_epoch = self._epoch
        if entry[1] != cur_epoch:
            # poisoned-era readback: _recover already failed its
            # requests and the donated device buffers it references may
            # be invalid — never materialize them. An insert entry
            # still releases its lease (idempotent) so recovery can
            # never leak a prefix-cache pin, and a preempt entry must
            # FAIL its evicted stream (between eviction and requeue it
            # lives only here — recovery could not see it).
            if entry[0] == "insert":
                self._release_lease(entry[2])
            elif entry[0] == "preempt":
                self._fail_orphan(entry[2], RuntimeError(
                    "engine recovered while this stream was preempted; "
                    "its evicted device state belonged to the poisoned "
                    "era"
                ))
            return
        self._fire("engine.harvest")
        if entry[0] == "insert":
            # prompt blocks back into the radix tree: materialize the
            # (already-local, copy kicked at dispatch) host bytes, split
            # the contiguous row window into per-block OWNED copies
            # (`.copy()` — a view would pin the whole window in RAM
            # while charging only block bytes), and attach. A failed
            # insert must never fail the request — the same device error
            # would already have surfaced through the request's own
            # prefill readback, which precedes this entry.
            _, _, req, first_new, rows = entry
            try:
                if rows is not None and self.prefix_cache is not None:
                    blk = self.prefix_cache.block_size
                    nb = len(req.prompt) // blk
                    full = tuple(
                        tuple(np.asarray(buf) for buf in layer)
                        for layer in rows
                    )
                    if self.paged:
                        blocks = _host_blocks(full, first_new, nb)
                    else:
                        blocks = [
                            tuple(
                                tuple(
                                    buf[:, j * blk:(j + 1) * blk].copy()
                                    for buf in layer
                                )
                                for layer in full
                            )
                            for j in range(first_new, nb)
                        ]
                    self.prefix_cache.insert(req.prompt, first_new, blocks)
            except Exception as exc:
                logger.info(f"prefix-cache insert skipped: {exc!r}")
            finally:
                self._release_lease(req)
            return
        if entry[0] == "preempt":
            # a preempted stream's evicted KV lands in the host block
            # store, the path is pinned against LRU, and the stream
            # re-enters the waiting room at the FRONT of its queue —
            # the resume admission then splices these exact bytes back
            # (pointer swaps, exact token parity; docs/robustness.md
            # "Preemption & fairness"). FIFO entry order guarantees
            # the insert lands before the re-admission can match.
            _, _, req, nb, rows, resume_prompt, incl = entry
            cache = self.prefix_cache
            try:
                if rows is not None and cache is not None and nb > 0:
                    full = tuple(
                        tuple(np.asarray(buf) for buf in layer)
                        for layer in rows
                    )
                    cache.insert(
                        resume_prompt, 0, _host_blocks(full, 0, int(nb))
                    )
            except Exception as exc:
                # a failed save must not fail the stream: the resume
                # admission simply matches fewer blocks and recomputes
                logger.info(f"preempt KV save skipped: {exc!r}")
            if cache is not None:
                # eviction-target pinning: the saved path must survive
                # LRU pressure until the resume admission takes its
                # own match lease over it
                self._release_resume_lease(req)  # a prior preemption's
                req._resume_lease = cache.lease(resume_prompt)
            req.prompt = resume_prompt
            req._prompt_incl = incl
            req._matched_blocks = 0
            req._park_logged = False
            self._flight_rec(
                "resume", rid=req.rid, tenant=req.tenant,
                priority=req.priority, tokens=len(req.tokens),
                cached_blocks=int(nb),
            )
            self._room.put(req, front=True)
            self._g_queue_depth.set(self._room.qsize())
            return
        if entry[0] == "prefill":
            _, _, slot, req, first = entry
            tok = int(np.asarray(first))
            now = time.perf_counter()  # after the readback: prefill_ms
            with self._lock:           # includes its in-flight lag
                req.prefill_ms = (now - req._dispatch_t) * 1e3
                if req.ttft_ms == 0.0:
                    # a RESUMED stream's first token already happened;
                    # its ttft must stay the first segment's
                    req.ttft_ms = (now - req.submitted) * 1e3
                req._prefill_end = now
                # ITL anchor: the next decode chunk's harvest spacing
                # measures from this first token (re-anchored here on
                # resume too, so the evict→resume gap never counts)
                req._itl_anchor = now
                self._tracer.record_span(
                    req.rid, "prefill", req._dispatch_t, now,
                    tokens=req._prefilled_tokens,
                )
                req.tokens.append(tok)
                req.emit([tok])
                if self._perf is not None:
                    self._perf.note_tokens(1)
                self._finish_if_done(slot, tok)
            if self._usage is not None:
                # the prefill's exclusive pipeline window (consecutive-
                # harvest spacing) + its dispatched programs' FLOPs,
                # billed wholly to the admitting tenant; the sampled
                # first token is that tenant's first served token
                device_s = max(
                    0.0,
                    now - max(req._dispatch_t, self._last_harvest_end),
                )
                self._last_harvest_end = now
                self._usage.attribute(
                    {req.tenant: 1}, device_s=device_s,
                    flops=req._attr_flops,
                )
                # drained: a resumed stream's next prefill segment
                # must not re-bill the first segment's programs
                req._attr_flops = 0.0
            return
        _, _, mask, gens, toks, dispatched, seq = entry
        if self.draft is not None:
            self._process_spec_chunk(mask, gens, toks, dispatched)
            return
        toks = np.asarray(toks)
        now = time.perf_counter()  # readback complete: the chunk landed
        self._h_harvest.observe((now - self._harvest_t0) * 1e3)
        tenant_tokens: dict = {}
        with self._lock:
            # slot-major (steps for different slots are independent): each
            # request's harvested tokens form ONE streamed chunk, emitted
            # before retirement so the stream's terminal sentinel follows
            # its final tokens
            for slot in np.flatnonzero(mask):
                req = self._occupant[slot]
                if req is None or gens[slot] != self._slot_gen[slot]:
                    continue  # stale: dispatched for a previous occupant
                chunk: List[int] = []
                for step_toks in toks:
                    tok = int(step_toks[slot])
                    req.tokens.append(tok)
                    chunk.append(tok)
                    if self._req_done(req, tok):
                        break
                self._tracer.record_span(
                    req.rid, f"decode-chunk[{req._chunk_i}]", dispatched, now,
                    tokens=len(chunk),
                )
                self._flight_rec(
                    "decode", rid=req.rid, tenant=req.tenant, slot=slot,
                    chunk=req._chunk_i, tokens=len(chunk),
                )
                req._chunk_i += 1
                req.emit(chunk)
                if self._perf is not None and chunk:
                    self._observe_itl(req, now, len(chunk))
                if self._usage is not None:
                    tenant_tokens[req.tenant] = (
                        tenant_tokens.get(req.tenant, 0) + len(chunk)
                    )
                self._finish_if_done(slot, chunk[-1])
            if self.paged:
                # this chunk (and by FIFO order every earlier one) has
                # been harvested: deferred frees fenced at or before it
                # are now safe — no in-flight program references them
                self._harvest_seq = max(self._harvest_seq, seq)
                self._sweep_deferred_locked()
        if self._usage is not None:
            # the chunk's exclusive pipeline window split by harvested-
            # token share; a chunk whose every slot went stale still
            # counts toward the unattributed totals (the identity
            # denominator stays honest under slot churn)
            device_s = max(
                0.0, now - max(dispatched, self._last_harvest_end)
            )
            self._last_harvest_end = now
            self._usage.attribute(
                tenant_tokens, device_s=device_s,
                flops=self._program_cost("engine.decode"),
                slot_steps=self.chunk_steps * self.slots,
            )

    def _process_spec_chunk(self, mask, gens, outs, dispatched) -> None:
        """Account one speculative chunk's readback: per round, each slot
        contributed ``n_emit`` tokens (variable — acceptance-dependent)
        from its ``emit`` row; budget truncation happens here exactly
        like the plain path's per-token ``_req_done`` walk."""
        emit, n_emit, accepted = (np.asarray(x) for x in outs)
        now = time.perf_counter()  # after np.asarray: readback complete
        self._h_harvest.observe((now - self._harvest_t0) * 1e3)
        tenant_tokens: dict = {}
        with self._lock:
            for slot in np.flatnonzero(mask):
                req = self._occupant[slot]
                if req is None or gens[slot] != self._slot_gen[slot]:
                    continue
                chunk: List[int] = []
                finished = False
                for r in range(emit.shape[0]):
                    if n_emit[r, slot] > 0:
                        # acceptance stats count only rounds whose tokens
                        # were actually SERVED (inside the gens check and
                        # before the budget break) — stale-generation and
                        # post-retirement overshoot rounds would skew the
                        # /stats acceptance_rate the benches report
                        self._m_spec_rounds.inc()
                        self._m_spec_accepted.inc(int(accepted[r, slot]))
                    for i in range(int(n_emit[r, slot])):
                        tok = int(emit[r, slot, i])
                        req.tokens.append(tok)
                        chunk.append(tok)
                        if self._req_done(req, tok):
                            finished = True
                            break
                    if finished:
                        break
                self._tracer.record_span(
                    req.rid, f"decode-chunk[{req._chunk_i}]", dispatched, now,
                    tokens=len(chunk),
                )
                self._flight_rec(
                    "decode", rid=req.rid, tenant=req.tenant, slot=slot,
                    chunk=req._chunk_i, tokens=len(chunk),
                )
                req._chunk_i += 1
                req.emit(chunk)
                if self._perf is not None and chunk:
                    self._observe_itl(req, now, len(chunk))
                if self._usage is not None and chunk:
                    tenant_tokens[req.tenant] = (
                        tenant_tokens.get(req.tenant, 0) + len(chunk)
                    )
                if chunk:
                    self._finish_if_done(slot, chunk[-1])
                elif req.abandoned:
                    # a fully-idle readback (device marked the slot done
                    # before any round) still must retire an abandoned
                    # waiter
                    self._finish_if_done(
                        slot, req.tokens[-1] if req.tokens else self.pad_id
                    )
        if self._usage is not None:
            device_s = max(
                0.0, now - max(dispatched, self._last_harvest_end)
            )
            self._last_harvest_end = now
            self._usage.attribute(
                tenant_tokens, device_s=device_s,
                flops=self._program_cost("engine.decode"),
                slot_steps=self.chunk_steps * self.slots,
            )

    def _dispatch_chunk(self) -> bool:
        """Dispatch one decode chunk if the pipeline has a credit and any
        occupant still needs tokens beyond already-dispatched work."""
        import jax.numpy as jnp

        if not self._chunk_credits.acquire(blocking=False):
            return False  # pipeline_depth chunks already awaiting harvest
        seq = 0
        table_np = None
        with self._lock:
            mask = np.array([r is not None for r in self._occupant])
            needed = any(
                r is not None and r._expected < r.max_new_tokens
                for r in self._occupant
            )
            ep0 = self._epoch
            st = self._state
            proceed = bool(mask.any()) and needed and st is not None
            if proceed and self.paged:
                # grow tables + snapshot + assign this chunk's fence seq
                # under ONE lock hold: a retirement racing this dispatch
                # fences its deferred frees at _dispatch_seq, which now
                # covers the snapshot we are about to launch — the
                # in-flight chunk can never write a recycled block
                table_np = self._grow_tables_locked()
                self._dispatch_seq += 1
                seq = self._dispatch_seq
        if not proceed:
            self._chunk_credits.release()
            return False
        t_dispatch = time.perf_counter()
        try:
            self._fire("engine.dispatch")
            keys = jnp.stack(self._next_key(self.chunk_steps))
            if self.paged:
                new_state, toks = self._decode_chunk(
                    self._params, st, jnp.asarray(mask),
                    jnp.asarray(table_np), keys,
                )
            else:
                new_state, toks = self._decode_chunk(
                    self._params, st, jnp.asarray(mask), keys
                )
            for leaf in toks if isinstance(toks, tuple) else (toks,):
                _start_host_copy(leaf)
            self._h_dispatch.observe((time.perf_counter() - t_dispatch) * 1e3)
        except BaseException:
            # the credit is only released by the harvester for entries that
            # were actually enqueued — give it back or the pipeline wedges
            self._chunk_credits.release()
            raise
        with self._lock:
            if self._epoch != ep0:
                # _recover ran (harvester thread) mid-dispatch: new_state
                # derives from the invalidated buffers — discard it
                # (self._state stays the recovery's None) and drop the
                # readback; the requests it covered are already failed
                self._chunk_credits.release()
                return True
            self._state = new_state
            for slot in np.flatnonzero(mask):
                if self._occupant[slot] is not None:
                    # the GUARANTEED emission per chunk (1 token/round in
                    # speculative mode — acceptance only adds more): an
                    # upper-bound here stops dispatching before enough
                    # tokens actually land at partial acceptance (hang,
                    # caught by test_spec_engine_matches_plain_greedy);
                    # over-dispatch at high acceptance is absorbed by the
                    # done mask + spare rows like any overshoot
                    self._occupant[slot]._expected += self.chunk_steps
                    if self.paged:
                        # host upper bound of the slot's device fill:
                        # next growth pass covers the following chunk
                        self._slot_rows[slot] = min(
                            self._slot_rows[slot] + self.chunk_steps,
                            self.cache_len,
                        )
            gens = tuple(self._slot_gen)
            self._m_chunks.inc()
            self._m_steps.inc(self.chunk_steps)
            occupied_now = int(mask.sum())
            self._m_occupied.inc(occupied_now * self.chunk_steps)
            if self._perf is not None:
                # goodput ring: classify this pass (full batch /
                # padded slots / prefill-mix) + KV pool pressure
                self._perf.note_pass(
                    occupied_now,
                    prefill_mix=self._admission is not None,
                    kv_in_use=(
                        self.kv_pool.in_use
                        if self.kv_pool is not None else 0
                    ),
                    kv_capacity=(
                        self.kv_pool.capacity
                        if self.kv_pool is not None else 0
                    ),
                )
        self._inflight.put(("chunk", ep0, mask, gens, toks, t_dispatch, seq))
        return True

    def _pop_request(self) -> Optional[_Request]:
        """Atomically dequeue a request and mark it as mid-admission, so
        bind()'s busy check never sees a gap where the request is neither
        queued nor occupying a slot."""
        self._fire("engine.dequeue")
        with self._lock:
            if None not in self._occupant:
                return None
            req = self._room.pop()
            if req is None:
                return None
            self._admitting += 1
        self._g_queue_depth.set(self._room.qsize())
        return req

    def _pop_bypass(self, parked: _Request) -> Optional[_Request]:
        """The PROMOTE path: while ``parked`` head-of-line-blocks its
        class on pool exhaustion, a STRICTLY higher-priority request
        may still admit past it (the waiting room's parked-lane gating
        releases nothing at or below the parked class) — without this,
        a premium request would wait out a bulk backlog's parked head
        in exactly the overload the scheduler exists for."""
        with self._lock:
            if None not in self._occupant:
                return None
            req = self._room.pop(
                above_rank=priority_rank(parked.priority)
            )
            if req is None:
                return None
            self._admitting += 1
        self._flight_rec(
            "promote", rid=req.rid, tenant=req.tenant,
            priority=req.priority, past=parked.rid,
            past_priority=parked.priority,
        )
        self._g_queue_depth.set(self._room.qsize())
        return req

    def _drop_admission(self, req: _Request, exc: BaseException) -> None:
        """Fail a request still mid-admission and release its count.
        Idempotent (keyed on the request event): the dispatcher's own
        error path and a concurrent ``_recover`` from the harvester must
        not double-release ``_admitting``."""
        with self._lock:
            if req.event.is_set():
                return
            req.error = exc
            self._admitting -= 1
            if self.paged:
                # the slot never became occupied, so every dispatched
                # chunk carried active=False for it (writes trash-routed
                # on device) — immediate free is safe
                self._drop_blocks_now_locked(req)
        self._release_lease(req)
        if req.abandoned:
            self._m_abandoned.inc()
            cause = "abandoned"
            if self._usage is not None:
                self._usage.record_drop(req.tenant, "abandoned")
        elif isinstance(exc, DeadlineExceeded):
            self._m_deadline_shed.inc()
            cause = "deadline_shed"
            if self._usage is not None:
                self._usage.record_deadline_shed(req.tenant)
        else:
            self._m_errors.inc()
            cause = f"error:{type(exc).__name__}"
            if self._usage is not None:
                self._usage.record_drop(req.tenant, "error")
        self._flight_rec("drop", rid=req.rid, tenant=req.tenant, cause=cause)
        self._tracer.finish_request(req.rid)
        req.event.set()
        req.finish_stream()

    # ------------------------------------------------------------------ #
    # preemption (docs/robustness.md "Preemption & fairness")
    # ------------------------------------------------------------------ #

    def _eligible_victims_locked(self) -> List:
        """Residents the scheduler may evict (lock held): prefill
        harvested (there is a token-exact resume point), waiter still
        listening, and the resume prompt — original prompt plus every
        harvested token — still fits an admission bucket (the splice
        path needs a ``[1, bucket]`` workspace)."""
        out = []
        for slot, r in enumerate(self._occupant):
            if r is None or r.abandoned or not r.tokens:
                continue
            if (
                len(r.prompt) + len(r.tokens) - r._prompt_incl
                > self.buckets[-1]
            ):
                continue
            out.append((slot, r))
        return out

    def _maybe_preempt(self, waiter: _Request) -> bool:
        """A parked (pool-exhausted) admission asks the scheduler to
        act: evict at most ONE strictly-lower-priority resident per
        dispatcher pass (gradual — each eviction frees blocks behind
        the dispatch fence, and the parked retry re-checks the pool
        every pass). Returns True when a victim was evicted."""
        if not self._preempt_enabled:
            return False
        with self._lock:
            # anti-cascade: blocks already freed onto the deferred
            # fence land as soon as the in-flight chunks harvest — if
            # they cover the waiter, a further eviction would thrash a
            # second victim for blocks that are already on their way
            pending = sum(len(ids) for _, ids in self._deferred_free)
            needed = self.kv_pool.blocks_for_rows(min(
                len(waiter.prompt) + waiter.max_new_tokens
                - len(waiter.tokens),
                self.cache_len,
            ))
            if self.kv_pool.available + pending >= needed:
                return False
            victim = self._sched.select_victim(
                waiter, self._eligible_victims_locked()
            )
        if victim is None:
            return False
        return self._preempt_victim(victim[0], victim[1], waiter)

    def _preempt_victim(
        self, slot: int, victim: _Request, waiter: _Request
    ) -> bool:
        """Evict ``victim`` from its slot so ``waiter`` can admit
        (dispatcher thread): gather the victim's finalized full KV
        blocks by table entry (the existing extract path — the async
        device→host copy starts now, the harvester materializes it),
        retire the slot with deferred-fence block frees (in-flight
        chunks may still write them), and hand the stream to the
        harvester's ``preempt`` entry, which stores the blocks in the
        host prefix cache and requeues the stream at the front of its
        queue. The resume admission splices the SAME bytes back, so
        the resumed stream's tokens are exactly its solo run's
        (chaos-tested in tests/unit/test_scheduler.py)."""
        import jax.numpy as jnp

        t0 = time.perf_counter()
        blk = self._kv_block_size
        with self._lock:
            if self._occupant[slot] is not victim or self._state is None:
                return False
            ep0 = self._epoch
            st = self._state
            # only FULL blocks whose every row is covered by harvested
            # tokens are saved: rows past prompt + new-tokens[:-1] may
            # be written by in-flight chunks mid-extract (same block),
            # so the sub-block tail is recomputed at resume instead —
            # the same recompute the warm-partial-hit admission path
            # runs. A resumed victim's prompt already CONTAINS its
            # first _prompt_incl tokens, so only the tail since the
            # last resume counts as new rows.
            nb = min(
                (
                    len(victim.prompt)
                    + len(victim.tokens) - victim._prompt_incl - 1
                ) // blk,
                self._slot_covered[slot],
            )
            ids = self._table[slot, :nb].copy()
        rows = None
        if nb > 0:
            # dispatched on the dispatcher thread BEFORE any later
            # decode chunk, so donation order guarantees it reads the
            # pre-eviction pool (the _schedule_insert precedent)
            rows = self._extract_blocks(st["pool"], jnp.asarray(ids))
            for layer in rows:
                for buf in layer:
                    _start_host_copy(buf)
        with self._lock:
            if self._epoch != ep0 or self._occupant[slot] is not victim:
                return False  # recovery/retirement raced: nothing evicted
            if (
                len(victim.prompt) + len(victim.tokens)
                - victim._prompt_incl > self.buckets[-1]
            ):
                # tokens harvested since the eligibility check pushed
                # the resume prompt past the largest bucket — evicting
                # now would fail the stream at re-admission (a caller-
                # visible error); leave it resident instead
                return False
            # stale-generation machinery: tokens from chunks already
            # in flight for this slot are discarded at harvest (they
            # are recomputed after resume), so the snapshot below is
            # the victim's final pre-eviction state
            self._slot_gen[slot] += 1
            self._occupant[slot] = None
            victim._preempts += 1
            victim._preempted_at = time.perf_counter()
            # unanchor ITL: the evict→resume gap is queueing, not
            # decode cadence — the resume prefill re-anchors
            victim._itl_anchor = 0.0
            resume_prompt = np.concatenate([
                victim.prompt,
                np.asarray(
                    victim.tokens[victim._prompt_incl:], np.int32
                ),
            ])
            incl = len(victim.tokens)
            freed = len(victim._block_ids)
            self._release_blocks_locked(victim, slot)
            self.kv_pool.note_preempted(freed)
            self._m_slots_busy.set(self._slots_in_use_locked())
        self._sched.record_preemption("priority")
        self._flight_rec(
            "preempt", rid=victim.rid, tenant=victim.tenant,
            priority=victim.priority, slot=slot, by=waiter.rid,
            by_priority=waiter.priority, blocks_saved=int(nb),
            blocks_freed=freed, tokens=len(victim.tokens),
        )
        self._tracer.record_span(
            victim.rid, f"preempt[{victim._preempts - 1}]", t0,
            time.perf_counter(), tokens=len(victim.tokens),
        )
        self._inflight.put(
            ("preempt", ep0, victim, nb, rows, resume_prompt, incl)
        )
        return True

    def _start_admission(self, req: _Request) -> None:
        """Dispatcher: begin admitting a dequeued request (counted in
        ``_admitting`` by ``_pop_request``). With a prefix cache, the
        longest cached block-prefix of the prompt is leased (pinned
        against eviction) and the admission becomes a block-granularity
        chunked one: the leading chunks are replaced by host-row
        splices, and only the uncovered suffix runs prefill programs.
        Otherwise short buckets prefill in one monolithic dispatch and
        buckets larger than ``prefill_chunk`` start a chunked admission
        whose lead chunks are dispatched one per loop pass, interleaved
        with decode chunks."""
        try:
            if req.abandoned:
                self._drop_admission(
                    req, TimeoutError("request abandoned before admission")
                )
                return
            if req.deadline is not None and time.perf_counter() > req.deadline:
                # shed at dequeue: an expired request must never consume
                # prefill (under overload that device time is exactly
                # what the live requests behind it need)
                waited_ms = (time.perf_counter() - req.submitted) * 1e3
                self._drop_admission(req, DeadlineExceeded(
                    f"request deadline expired while queued "
                    f"(waited {waited_ms:.0f} ms)",
                    deadline_ms=(req.deadline - req.submitted) * 1e3,
                ))
                return
            self._fire("engine.prefill")
            if self.paged and not req._block_ids and req._resv_blocks == 0:
                # reserve the WORST-CASE block count up front so table
                # growth can never fail mid-decode; a transiently full
                # pool PARKS the admission (retried every dispatcher
                # pass, FIFO preserved — nothing admits past it) until
                # retirements free blocks. Queue backlog behind a
                # parked admission sheds through max_queue_depth.
                # a RESUMED stream's prompt already contains its
                # harvested tokens, so it only decodes the remainder —
                # without the subtraction a resume could demand more
                # than the whole pool and park forever
                rows_cap = min(
                    len(req.prompt) + req.max_new_tokens - len(req.tokens),
                    self.cache_len,
                )
                needed = self.kv_pool.blocks_for_rows(rows_cap)
                with self._lock:
                    try:
                        # retries of a parked admission count neither a
                        # new alloc failure nor a new flight event —
                        # one pool-pressure incident per park
                        self.kv_pool.reserve(
                            needed, count_failure=not req._park_logged
                        )
                    except PoolExhausted as exc:
                        self._room.park(req)
                        if not req._park_logged:
                            req._park_logged = True
                            resident = [
                                r for r in self._occupant if r is not None
                            ]
                            cand = (
                                min(resident, key=lambda r: r.submitted)
                                if resident else None
                            )
                            # post-hoc 429 analysis: distinguishes
                            # pool-full from queue-full, and names the
                            # oldest-resident candidate; the SCHEDULER
                            # acts on its own victim policy when a
                            # strictly lower-priority resident exists
                            # (docs/robustness.md)
                            self._flight_rec(
                                "pool_pressure", reason="alloc_fail",
                                rid=req.rid, priority=req.priority,
                                needed_blocks=exc.needed,
                                available_blocks=exc.available,
                                preempt_candidate=(
                                    cand.rid if cand is not None else None
                                ),
                                preempt_candidate_blocks=(
                                    len(cand._block_ids)
                                    if cand is not None else 0
                                ),
                            )
                        return
                    req._resv_blocks = needed
                    req._rows_cap = rows_cap
                    req._park_logged = False
                    req._pool_gen = self.kv_pool.generation
            # the resident state inits lazily inside _admit / the final
            # chunk of _advance_admission (NOT here: an unlocked write
            # would race a concurrent _recover's reset)
            cache, m_used = self.prefix_cache, 0
            bucket = self._bucket_for(len(req.prompt))
            chunk = self.prefill_chunk
            # cached-admission granularity: the cache block for
            # monolithic-class buckets, prefill_chunk for chunked ones —
            # a cached long prompt must never degrade its suffix to
            # block-sized programs (a small hit would then admit far
            # SLOWER than a miss). Buckets are lcm(block, chunk)-rounded
            # at construction, so unit-aligned starts are block-aligned.
            unit = None
            if cache is not None:
                unit = cache.block_size
                if chunk is not None and bucket > chunk:
                    # must stay block-representable AND chunk-aligned;
                    # == prefill_chunk whenever block divides it (the
                    # common case — same compiled shapes as a miss)
                    unit = math.lcm(unit, chunk)
                lease = cache.match(req.prompt)
                req._lease = lease
                req._matched_blocks = lease.n_blocks
                # the resume pin's job is done: the admission's own
                # match lease now covers the same path
                self._release_resume_lease(req)
                blk = cache.block_size
                # usable match: unit-quantized, and capped one token
                # short of the prompt — finish_prefill must run at
                # least the last real token to sample token 0 from it
                m_used = min(
                    lease.n_blocks, (len(req.prompt) - 1) // blk
                ) * blk // unit * unit
            # credited to the tokens-saved counter at admission
            # completion (_advance_admission), not here: a dropped or
            # abandoned admission saved nothing
            req._saved_tokens = m_used
            req._prefilled_tokens = len(req.prompt) - m_used
            if m_used == 0 and (chunk is None or bucket <= chunk):
                self._admit(req)
                with self._lock:
                    self._admitting -= 1
                return
            slot, bucket, padded = self._admission_preamble(req)
            # only the chunks covering the TRUE length run — a short
            # prompt routed into a long bucket pays for its own length
            # (and a cached admission only the uncovered suffix)
            chunk_use = unit if m_used else chunk
            if m_used:
                # group the matched blocks into unit-sized splice
                # entries (one device dispatch per unit, memoized
                # host→device via _dev_splice)
                g = unit // cache.block_size
                splice_rows = [
                    tuple(req._lease.rows[u * g:(u + 1) * g])
                    for u in range(m_used // unit)
                ]
            else:
                splice_rows = []
            n_chunks = -(-len(req.prompt) // chunk_use)
            pool_ids = None
            if self.paged:
                with self._lock:
                    pool_ids = self._take_covered_locked(req, slot, bucket)
            adm = _Admission(
                req=req, slot=slot, bucket=bucket, chunk=chunk_use,
                n_chunks=n_chunks, padded=padded,
                fresh=self._init_fresh(bucket=bucket),
                pool_ids=pool_ids,
                next_chunk=m_used // chunk_use,
                splice_rows=splice_rows,
            )
            with self._lock:
                self._admission = adm
        except BaseException as exc:
            with self._lock:
                self._admission = None
            self._drop_admission(req, exc)

    def _advance_admission(self, adm: _Admission) -> None:
        """Dispatch ONE step of the in-progress admission — a cached
        block splice, a lead prefill chunk, or the final chunk that
        finishes into the slot; decode chunks dispatch between calls, so
        resident slots never stall behind a long prompt's prefill.
        ``_recover``/``close`` may concurrently null ``_admission`` —
        every transition re-checks identity under the lock so the
        admission is completed or dropped exactly once."""
        import jax.numpy as jnp

        req = adm.req
        try:
            if req.abandoned:
                with self._lock:
                    if self._admission is not adm:
                        return
                    self._admission = None
                self._drop_admission(
                    req, TimeoutError("request abandoned during admission")
                )
                return
            self._fire("engine.prefill")
            if adm.next_splice < len(adm.splice_rows):
                # cached-prefix unit: device-resident rows (memoized
                # host→device upload) spliced into the fresh cache in
                # place of the prefill program that would have
                # recomputed them
                i = adm.next_splice
                t0 = time.perf_counter()
                rows = self._device_splice_rows(adm.splice_rows[i])
                adm.fresh = self._splice_block(
                    adm.fresh, rows, jnp.int32(i * adm.chunk)
                )
                adm.next_splice += 1
                self._tracer.record_span(
                    req.rid, f"prefix-splice[{i}]", t0, time.perf_counter(),
                    tokens=adm.chunk,
                )
                return
            start = adm.next_chunk * adm.chunk
            toks = jnp.asarray(adm.padded[None, start: start + adm.chunk])
            if adm.next_chunk < adm.n_chunks - 1:
                t0 = time.perf_counter()
                adm.fresh = self._prefill_step(
                    self._params, adm.fresh, toks, jnp.int32(start)
                )
                if self._usage is not None:
                    req._attr_flops += self._program_cost(
                        "engine.prefill_chunk", tuple(toks.shape)
                    )
                self._tracer.record_span(
                    req.rid, f"prefill-chunk[{adm.next_chunk}]", t0,
                    time.perf_counter(), tokens=adm.chunk,
                )
                adm.next_chunk += 1
                return
            (key,) = self._next_key()
            with self._lock:
                ep0 = self._epoch
                st = self._state
                if self._admission is not adm:
                    # raced with _recover/close: the request was already
                    # failed and its count released — do not re-admit
                    return
            if st is None:
                # first admission ever, or a recovery dropped the
                # resident state while this admission was mid-flight but
                # BEFORE it was registered (so _recover could not drop
                # it): build it fresh and proceed — returning here
                # instead would strand the admission (never completed,
                # never dropped) and wedge the engine
                st = self._init_state()
            if self.paged:
                new_state, first = self._prefill_final(
                    self._params, st, adm.fresh, jnp.int32(adm.slot),
                    jnp.asarray(adm.pool_ids), toks, jnp.int32(start),
                    jnp.int32(len(req.prompt)), key,
                )
            else:
                new_state, first = self._prefill_final(
                    self._params, st, adm.fresh, jnp.int32(adm.slot),
                    toks, jnp.int32(start), jnp.int32(len(req.prompt)), key,
                )
            _start_host_copy(first)
            if self._usage is not None:
                req._attr_flops += self._program_cost(
                    "engine.prefill_final", tuple(toks.shape)
                )
            with self._lock:
                if self._admission is not adm or self._epoch != ep0:
                    # raced with _recover/close mid-dispatch: the request
                    # was already failed, and new_state derives from the
                    # invalidated buffers — discard it (self._state stays
                    # the recovery's None)
                    return
                self._state = new_state
                self._admission = None
                self._occupant[adm.slot] = req
                self._slot_gen[adm.slot] += 1
                # resumed streams already hold harvested tokens;
                # dispatch accounting continues from them
                req._expected = len(req.tokens) + 1
                self._admitting -= 1
                self._m_slots_busy.set(self._slots_in_use_locked())
            # admission segment: dispatch start → final prefill chunk
            # enqueued (covers every interleaved lead chunk + splice)
            req.admission_ms = (
                (time.perf_counter() - req._dispatch_t) * 1e3
            )
            self._flight_rec(
                "prefill", rid=req.rid, tenant=req.tenant, slot=adm.slot,
                bucket=adm.bucket, tokens=req._prefilled_tokens,
                cached_tokens=req._saved_tokens, chunks=adm.n_chunks,
            )
            self._inflight.put(("prefill", ep0, adm.slot, req, first))
            self._schedule_insert(req, adm.slot, ep0)
            if self.prefix_cache is not None and req._saved_tokens:
                # the admission actually completed on spliced rows —
                # NOW the skipped prefill work is real
                self.prefix_cache.record_saved_tokens(req._saved_tokens)
        except BaseException as exc:
            with self._lock:
                if self._admission is adm:
                    self._admission = None
            self._drop_admission(req, exc)

    def _advance_admission_budgeted(self, adm: _Admission) -> None:
        """One dispatcher pass of admission work under the scheduler's
        stall-free mixing budget: with ``mix_prefill_tokens`` unset
        (default) exactly one admission step runs per pass — the
        historical cadence — else lead prefill chunks keep dispatching
        until the token budget is spent (splices are pointer swaps and
        never charge it), so long prompts admit faster while decode
        chunks still interleave every pass."""
        budget = self._mix_budget
        if budget is None:
            self._advance_admission(adm)
            return
        remaining = budget
        while self._admission is adm:
            was_splice = adm.next_splice < len(adm.splice_rows)
            self._advance_admission(adm)
            if not was_splice:
                remaining -= adm.chunk
                if remaining <= 0:
                    break

    def _run(self):
        """Dispatcher: admit queued requests into free slots and keep up
        to ``pipeline_depth`` decode chunks in flight. NEVER blocks on a
        readback — the harvester thread owns those. Through a tunneled
        backend a readback interaction costs a full round trip (~119 ms
        measured vs ~2 ms/step of decode compute, BASELINE.md), so
        overlapping dispatch with harvest is what keeps the chip busy;
        ``is_ready`` polling is worse than blocking (it serializes the
        command stream) and is never used.
        """
        while not self._stop.is_set():
            try:
                progressed = False
                adm = self._admission
                if adm is not None:
                    # stall-free mixing (Sarathi lineage): up to the
                    # configured prefill token budget of admission
                    # steps per pass, then a decode chunk — resident
                    # slots keep streaming under any budget
                    self._advance_admission_budgeted(adm)
                    progressed = True
                else:
                    # a parked admission (pool exhausted at
                    # reservation) retries FIRST; the waiting room
                    # only releases strictly-higher-priority requests
                    # past it, so FIFO-under-pressure survives within
                    # and below the parked class
                    req = None
                    with self._lock:
                        has_slot = None in self._occupant
                    if has_slot:
                        req = self._room.take_parked()
                    if req is None:
                        req = self._pop_request()
                    if req is not None:
                        self._start_admission(req)
                        if self._room.is_parked(req):
                            # pool exhausted: EVICTING a strictly
                            # lower-priority resident is progress;
                            # otherwise sleep and retry once
                            # retirements free blocks
                            progressed = self._maybe_preempt(req)
                            # promote: a strictly-higher-priority
                            # request may admit past the parked head
                            # (it may itself park — joining the lane —
                            # and preempt on its own behalf)
                            breq = self._pop_bypass(req)
                            if breq is not None:
                                self._start_admission(breq)
                                if self._room.is_parked(breq):
                                    progressed = (
                                        self._maybe_preempt(breq)
                                        or progressed
                                    )
                                else:
                                    progressed = True
                        else:
                            progressed = True
                if self._dispatch_chunk():
                    progressed = True
                if not progressed:
                    # nothing admittable or dispatchable: arrivals and
                    # harvest-freed slots are picked up next pass (2 ms
                    # keeps the 1-core host responsive without spinning)
                    if self._perf is not None:
                        # goodput ring: the device is parked this pass
                        self._perf.note_idle()
                    time.sleep(0.002)
            except BaseException as exc:  # pragma: no cover - engine crash
                self._recover(exc)

    def _harvest_loop(self):
        """Harvester: block on the oldest in-flight readback, account its
        tokens, retire finished requests, release the pipeline credit."""
        while not self._stop.is_set():
            try:
                entry = self._inflight.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                self._process_entry(entry)
            except BaseException as exc:  # pragma: no cover - engine crash
                self._recover(exc)
            finally:
                if entry[0] == "chunk":
                    self._chunk_credits.release()

    def _recover(self, exc: BaseException) -> None:
        """Engine supervision (replaces the old terminal ``_fail_all``):
        a failed device program fails ONLY the poisoned batch — the
        resident occupants and the in-progress admission, whose donated
        device state the error invalidated — then bumps the readback
        epoch (in-flight entries from the poisoned era are skipped at
        harvest, leases released) and drops the decode state so the
        next admission rebuilds it; queued requests were never touched
        and re-admit as survivors. Each recovery feeds the circuit
        breaker: ``breaker_threshold`` of them within
        ``breaker_window_s`` (with no successful completion in between)
        open it for ``breaker_cooldown_s``."""
        t0 = time.perf_counter()
        logger.info(
            f"decode engine error: {exc!r} — failing the poisoned batch "
            "and rebuilding decode state"
        )
        poisoned: List[str] = []
        with self._lock:
            adm, self._admission = self._admission, None
        if adm is not None:
            poisoned.append(adm.req.rid)
            self._drop_admission(adm.req, exc)
        with self._lock:
            self._epoch += 1
            for slot, req in enumerate(self._occupant):
                if req is not None:
                    poisoned.append(req.rid)
                    req.error = exc
                    self._m_errors.inc()
                    self._tracer.finish_request(req.rid)
                    self._release_lease(req)
                    if self._usage is not None:
                        # close the hold window and bill the drop before
                        # the pool bookkeeping is reset under it
                        self._usage_kv_release(req)
                        self._usage.record_drop(req.tenant, "error")
                    # pool bookkeeping resets wholesale below — zero the
                    # per-request fields so nothing double-frees
                    req._block_ids = []
                    req._resv_blocks = 0
                    req.event.set()
                    req.finish_stream()
                    self._occupant[slot] = None
            self._m_slots_busy.set(0)
            self._state = None
            if self.paged:
                # the device pool arrays died with the donated state;
                # the next admission's _init_state rebuilds them, so
                # host bookkeeping resets with them (in-flight poisoned
                # readbacks are epoch-skipped and write dead buffers)
                self.kv_pool.reset()
                self._table[:] = 0
                self._slot_covered = [0] * self.slots
                self._slot_rows = [0] * self.slots
                self._deferred_free = []
                self._harvest_seq = self._dispatch_seq
            self._m_recoveries.inc()
            now = time.monotonic()
            self._recovery_times.append(now)
            while (
                self._recovery_times
                and now - self._recovery_times[0] > self.breaker_window_s
            ):
                self._recovery_times.popleft()
            if len(self._recovery_times) >= self.breaker_threshold:
                self._breaker_open_until = now + self.breaker_cooldown_s
                self._g_breaker.set(1.0)
                logger.info(
                    f"engine circuit breaker OPEN: "
                    f"{len(self._recovery_times)} recoveries within "
                    f"{self.breaker_window_s}s; rejecting submissions "
                    f"for {self.breaker_cooldown_s}s"
                )
        # the recovery itself is a traceable event (spans are how the
        # PR-1 telemetry narrates a request timeline; recoveries get
        # their own synthetic timeline) — with the flight-recorder
        # snapshot of the poisoned requests' lifecycle attached, so the
        # postmortem names WHO died and what they were doing when the
        # device program failed
        span_args: dict = {
            "error": repr(exc)[:200], "poisoned": list(poisoned),
        }
        if self._flight is not None:
            self._flight_rec(
                "recovery", rids=list(poisoned), error=repr(exc)[:200],
            )
            span_args["flight"] = self._flight.snapshot(poisoned)
        rid = self._tracer.new_request("recovery")
        self._tracer.record_span(
            rid, "recover", t0, time.perf_counter(), **span_args
        )
        self._tracer.finish_request(rid)
