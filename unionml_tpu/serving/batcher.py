"""On-device micro-batcher: coalesce requests, pad to buckets, one call.

No reference counterpart — unionml/fastapi.py:50-64 runs one predictor
call per HTTP request. On TPU that wastes the MXU (batch-1 matmuls) and
pays dispatch latency per request. This batcher:

1. queues concurrent requests,
2. drains up to ``max_batch_size`` of them (waiting at most
   ``max_wait_ms`` after the first arrival),
3. concatenates features along the batch axis and right-pads to the next
   **bucket size** so XLA compiles exactly ``len(buckets)`` executables
   (SURVEY.md §7 hard part (e): bucketed shapes vs. recompilation),
4. runs the predictor once, splits results back per-request.

Thread-based (works under any transport, stdlib or ASGI); the device call
itself is serialized, which is the desired behavior on a single chip.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from unionml_tpu import telemetry
from unionml_tpu._logging import logger
from unionml_tpu.serving.faults import (
    DeadlineExceeded,
    EngineUnavailable,
    Overloaded,
    current_deadline_ms,
)
from unionml_tpu.serving.usage import DEFAULT_TENANT, current_tenant

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64)

# batch-size histogram bounds: the row counts are small powers of two
# (bucketed shapes), so the ms buckets would waste resolution
BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def _leading_dim(features: Any, row_lists: bool) -> int:
    if row_lists:
        return len(features)
    import jax

    leaves = jax.tree_util.tree_leaves(features)
    return int(leaves[0].shape[0]) if leaves else 0


def _concat(items: Sequence[Any], row_lists: bool) -> Any:
    if row_lists:
        out: list = []
        for i in items:
            out.extend(i)
        return out
    import jax

    return jax.tree_util.tree_map(lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0), *items)


def _pad_to(features: Any, n: int, row_lists: bool) -> Any:
    if row_lists:
        # replicate the last row; the split below drops padded results
        return list(features) + [features[-1]] * (n - len(features))
    import jax

    def pad(x):
        x = np.asarray(x)
        if x.shape[0] >= n:
            return x
        pad_width = [(0, n - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        return np.pad(x, pad_width, mode="edge")

    return jax.tree_util.tree_map(pad, features)


def _slice_rows(result: Any, start: int, stop: int, row_lists: bool) -> Any:
    if row_lists:
        return list(result)[start:stop]
    import jax

    # array mode: lists/tuples/dicts are pytree STRUCTURE; every leaf
    # slices along its batch axis
    return jax.tree_util.tree_map(lambda x: np.asarray(x)[start:stop], result)


@dataclass
class _Pending:
    features: Any
    rows: int
    event: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None
    submitted: float = 0.0
    queue_wait_ms: float = 0.0
    device_ms: float = 0.0
    # waiter gave up (submit timeout): skip at drain time instead of
    # burning a device call on a result nobody will read (mirrors the
    # engine's req.abandoned convention)
    abandoned: bool = False
    # absolute perf_counter deadline (None = none): enforced at drain,
    # so an expired entry is shed before joining a device batch
    deadline: Optional[float] = None
    # telemetry trace timeline (created on the submitting thread, so it
    # inherits the transport's W3C trace scope): queue + predict spans
    rid: str = ""
    # usage metering: the validated tenant this entry's share of the
    # batched device call is billed to (the ambient tenant_scope)
    tenant: str = DEFAULT_TENANT


class MicroBatcher:
    """Coalesce concurrent predict calls into bucketed device batches."""

    def __init__(
        self,
        predict_fn: Callable[[Any], Any],
        *,
        max_batch_size: int = 64,
        max_wait_ms: float = 5.0,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        row_lists: bool = False,
        registry: Optional[telemetry.MetricsRegistry] = None,
        tracer: Optional[telemetry.TraceRecorder] = None,
        max_queue_depth: Optional[int] = None,
        fault_injector=None,
        introspect: bool = True,
        flight=None,
        usage=None,
    ):
        """``row_lists=True``: features/results are plain Python lists of
        per-example rows (possibly ragged — LLM token-id prompts), so the
        batcher coalesces by list concat instead of array concat. Use for
        predictors with the make_lm_predictor contract.

        ``registry``: explicit telemetry sink; defaults to the
        process-global registry so ``GET /metrics`` covers this batcher
        (series isolated per instance by the ``batcher`` label).

        ``tracer``: explicit :class:`~unionml_tpu.telemetry
        .TraceRecorder`; defaults to the process-global one. Every
        ``submit()`` opens a request timeline on the SUBMITTING thread
        — so it joins the transport's W3C
        :func:`~unionml_tpu.telemetry.trace_scope` when one is open —
        and records ``queue`` and ``predict`` spans around the shared
        device call.

        ``max_queue_depth``: admission control — a ``submit()`` that
        would push the not-yet-batched queue past this many entries
        raises :class:`~unionml_tpu.serving.faults.Overloaded` instead
        of queueing forever (the transports map it to HTTP 429 with
        ``Retry-After``). ``None`` keeps the historical unbounded queue.

        ``fault_injector``: a :class:`~unionml_tpu.serving.faults
        .FaultInjector` whose ``batcher.predict`` point fires before
        the shared device call (chaos tests; ``None`` is zero-cost).

        ``introspect``: wrap the predictor in a
        :class:`~unionml_tpu.introspection.ProgramTracker` (compile
        events record XLA cost-analysis flops/bytes; ``stats()
        ["programs"]`` and the ``unionml_program_*`` series report
        them) and record request lifecycle events into ``flight``
        (default: the process-global
        :class:`~unionml_tpu.telemetry.FlightRecorder` behind
        ``GET /debug/flight``). ``False`` disables both.

        ``usage``: a :class:`~unionml_tpu.serving.usage.UsageLedger`
        (or ``True`` for a default one on this batcher's registry)
        enabling per-tenant usage metering: each entry's queue wait,
        row count, and share of the batched device call (device-seconds
        and tracker FLOPs, split by row share) are billed to the
        ambient :func:`~unionml_tpu.serving.usage.tenant_scope` tenant;
        ``None`` (default) disables metering."""
        self._predict_fn = predict_fn
        self.row_lists = row_lists
        self.max_batch_size = max_batch_size
        self.max_wait_s = max_wait_ms / 1000.0
        self.buckets = tuple(sorted(set(buckets) | {max_batch_size}))
        if max_queue_depth is not None and max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1 when set")
        self.max_queue_depth = max_queue_depth
        self._faults = fault_injector
        self._draining = False
        # admission lock: depth-check + enqueue must be atomic, or N
        # concurrent submitters each pass the check and push the queue
        # past the bound. _pending counts undisposed entries (queued OR
        # inside the worker's device call) — what drain() must wait on;
        # queue.empty() alone returns early while the last batch is
        # still on device.
        self._admit_lock = threading.Lock()
        self._pending = 0
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        self._stop = threading.Event()
        self._registry = registry if registry is not None else telemetry.get_registry()
        self._tracer = tracer if tracer is not None else telemetry.get_tracer()
        self.instance = telemetry.instance_label("batcher")
        self._build_instruments()
        # program introspection + flight recording (docs/observability
        # .md): the tracker detects compiles of a jitted predictor and
        # records cost-analysis flops/bytes; a plain-Python predictor is
        # tracked opaquely (calls only). introspect=False leaves the
        # predictor unwrapped and every flight site a single None check.
        self.introspect = bool(introspect)
        if usage is True:
            from unionml_tpu.serving.usage import UsageLedger

            usage = UsageLedger(registry=self._registry)
        self._usage = usage or None
        self._programs = None
        self._flight = None
        if self.introspect:
            from unionml_tpu.introspection import ProgramTracker

            self._flight = (
                flight if flight is not None
                else telemetry.get_flight_recorder()
            )
            self._programs = ProgramTracker(
                registry=self._registry, component=self.instance
            )
            self._predict_fn = self._programs.wrap(
                "batcher.predict", self._predict_fn,
                # cheap per-call signature: the padded bucket size (row
                # lists) or the leading array shape; pytree features
                # fall back to single-signature attribution
                sig_fn=(
                    (lambda feats: len(feats)) if row_lists
                    else (lambda feats: getattr(feats, "shape", None))
                ),
            )
        self._worker = threading.Thread(target=self._run, daemon=True, name="unionml-tpu-batcher")
        self._worker.start()

    def _build_instruments(self):
        R, lbl = self._registry, {"batcher": self.instance}

        def counter(name, help):
            return R.counter(name, help, ("batcher",)).labels(**lbl)

        self._m_requests = counter(
            "unionml_batcher_requests_total",
            "Requests completed through a batched device call.",
        )
        self._m_errors = counter(
            "unionml_batcher_errors_total",
            "Requests failed by a predictor/batcher error.",
        )
        self._m_abandoned = counter(
            "unionml_batcher_abandoned_total",
            "Requests whose submit() timed out before the batch ran "
            "(skipped at drain time, no device call burned).",
        )
        self._m_batches = counter(
            "unionml_batcher_batches_total", "Batched device calls.",
        )
        self._m_rows = counter(
            "unionml_batcher_batched_rows_total",
            "Rows coalesced into batched device calls.",
        )
        self._h_batch = R.histogram(
            "unionml_batcher_batch_rows",
            "Rows per batched device call (pre-padding).",
            ("batcher",), buckets=BATCH_SIZE_BUCKETS,
        ).labels(**lbl)
        self._h_queue = R.histogram(
            "unionml_batcher_queue_wait_ms",
            "Submit-to-batch-start wait per request.", ("batcher",),
        ).labels(**lbl)
        self._h_device = R.histogram(
            "unionml_batcher_device_ms",
            "Shared batched device-call time per request.", ("batcher",),
        ).labels(**lbl)
        rejected = R.counter(
            "unionml_batcher_rejected_total",
            "submit() calls rejected at admission control, by reason "
            "(queue_full -> 429, draining -> 503).",
            ("batcher", "reason"),
        )
        self._m_rejected = {
            reason: rejected.labels(batcher=self.instance, reason=reason)
            for reason in ("queue_full", "draining")
        }
        self._m_deadline_shed = counter(
            "unionml_batcher_deadline_shed_total",
            "Entries shed at batch-drain time because their deadline "
            "expired before the device call.",
        )
        self._g_queue_depth = R.gauge(
            "unionml_batcher_queue_depth",
            "Entries queued awaiting a batch.", ("batcher",),
        ).labels(**lbl)

    def _flight_rec(self, kind: str, **fields) -> None:
        """O(1) flight-recorder append (no-op when introspect=False)."""
        if self._flight is not None:
            self._flight.record(kind, batcher=self.instance, **fields)

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def submit(
        self,
        features: Any,
        timeout: Optional[float] = 60.0,
        *,
        deadline_ms: Optional[float] = None,
    ) -> Any:
        """Block until the batched prediction for ``features`` is ready.

        A timed-out submit marks its entry **abandoned**: the worker
        skips it at drain time (``batcher_abandoned_total``) instead of
        burning a device call on a result nobody will read.

        Admission control: while draining, raises
        :class:`~unionml_tpu.serving.faults.EngineUnavailable`; with
        ``max_queue_depth`` set and the queue full, raises
        :class:`~unionml_tpu.serving.faults.Overloaded`. A deadline
        (explicit ``deadline_ms``, or the ambient
        :func:`~unionml_tpu.serving.faults.deadline_scope` the HTTP
        layer opens from ``X-Deadline-Ms``) sheds the entry with
        :class:`~unionml_tpu.serving.faults.DeadlineExceeded` if it
        expires before the device call starts."""
        if deadline_ms is None:
            deadline_ms = current_deadline_ms()
        pending = _Pending(
            features=features, rows=_leading_dim(features, self.row_lists),
            submitted=time.perf_counter(), tenant=current_tenant(),
        )
        if deadline_ms is not None:
            pending.deadline = pending.submitted + deadline_ms / 1e3
        with self._admit_lock:
            if self._draining:
                self._m_rejected["draining"].inc()
                if self._usage is not None:
                    self._usage.record_rejected(pending.tenant, "draining")
                self._flight_rec(
                    "reject", reason="draining", tenant=pending.tenant,
                )
                raise EngineUnavailable(
                    "micro-batcher is draining and not accepting requests",
                    reason="draining", retry_after_s=1.0,
                )
            if self.max_queue_depth is not None:
                depth = self._queue.qsize()
                if depth >= self.max_queue_depth:
                    self._m_rejected["queue_full"].inc()
                    if self._usage is not None:
                        self._usage.record_rejected(
                            pending.tenant, "queue_full"
                        )
                    self._flight_rec(
                        "reject", reason="queue_full", queue_depth=depth,
                        tenant=pending.tenant,
                    )
                    raise Overloaded(
                        f"micro-batcher queue is full ({depth} queued >= "
                        f"max_queue_depth {self.max_queue_depth})",
                        retry_after_s=max(0.1, self.max_wait_s),
                    )
            # recorded BEFORE the put (the worker drains the queue
            # without this lock): the entry's 'submit' flight event
            # always precedes its 'batch'/'drop'. queue_depth = entries
            # ahead of this one.
            # created on the submitting thread INSIDE admission: it
            # inherits the transport's ambient trace scope, and a
            # rejected submit never opens a timeline to leak
            pending.rid = self._tracer.new_request(
                "batch", batcher=self.instance, rows=pending.rows
            )
            self._flight_rec(
                "submit", rows=pending.rows, tenant=pending.tenant,
                queue_depth=self._queue.qsize(),
            )
            self._queue.put(pending)
            self._pending += 1
        self._g_queue_depth.set(self._queue.qsize())
        if not pending.event.wait(timeout):
            pending.abandoned = True
            raise TimeoutError("micro-batcher did not produce a result in time")
        if pending.error is not None:
            raise pending.error
        return pending.result

    def health(self) -> dict:
        """Readiness surface for ``GET /health`` (same shape as
        :meth:`DecodeEngine.health <unionml_tpu.serving.engine
        .DecodeEngine.health>`; the batcher has no device state to
        rebuild, so no circuit breaker — ``breaker_open`` is always
        False)."""
        return {
            "status": "draining" if self._draining else "ok",
            "queue_depth": self._queue.qsize(),
            "breaker_open": False,
        }

    def _dispose(self, n: int = 1) -> None:
        """An entry left the system (delivered, errored, shed, or
        skipped as abandoned): retire its drain() obligation."""
        with self._admit_lock:
            self._pending -= n

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful drain: stop admitting (``submit()`` raises
        :class:`~unionml_tpu.serving.faults.EngineUnavailable`), then
        block until every accepted entry has been **delivered** — the
        pending count covers the batch inside the device call too, not
        just the queue (a cold compile can hold it for seconds; a
        queue-only check would hand shutdown a worker mid-call).
        Returns True when drained, False on ``timeout``."""
        t0 = time.perf_counter()
        self._draining = True
        while True:
            with self._admit_lock:
                if self._pending == 0:
                    return True
            if timeout is not None and time.perf_counter() - t0 > timeout:
                return False
            time.sleep(0.005)

    def resume(self) -> None:
        """Reopen admissions after :meth:`drain`."""
        self._draining = False

    def stats(self) -> dict:
        """Serving observability: queue-wait vs device-time split.

        A thin view over this instance's telemetry-registry series (the
        same numbers ``GET /metrics`` exposes), keeping the historical
        key shape."""
        batches = int(self._m_batches.value)
        out = {
            "engine": "micro-batch",
            "completed_requests": int(self._m_requests.value),
            "batches": batches,
            "mean_batch_rows": round(
                int(self._m_rows.value) / max(1, batches), 2
            ),
            "robustness": {
                "queue_depth": self._queue.qsize(),
                "rejected": {
                    reason: int(c.value)
                    for reason, c in self._m_rejected.items()
                },
                "deadline_shed": int(self._m_deadline_shed.value),
                "draining": self._draining,
            },
        }
        if self._usage is not None:
            out["usage"] = self._usage.stats()
        if self._programs is not None:
            out["programs"] = self._programs.stats()
        for name, h in (
            ("queue_wait_ms", self._h_queue), ("device_ms", self._h_device)
        ):
            summary = h.summary()
            if summary:
                out[name] = summary
        return out

    def reset_stats(self) -> None:
        """Zero this instance's observability series (benchmarks call
        this between scenarios so each phase's /stats describes only
        that phase); scrapers see the resets as counter restarts."""
        for m in (
            self._m_requests, self._m_errors, self._m_abandoned,
            self._m_batches, self._m_rows, self._m_deadline_shed,
            *self._m_rejected.values(), self._h_batch, self._h_queue,
            self._h_device,
        ):
            m.reset()
        if self._usage is not None:
            self._usage.reset_stats()
        if self._programs is not None:
            self._programs.reset()

    def close(self):
        self._stop.set()
        self._worker.join(timeout=1.0)
        # fail fast for requests still queued instead of letting their
        # submit() calls block until timeout
        while True:
            try:
                pending = self._queue.get_nowait()
            except queue.Empty:
                break
            pending.error = RuntimeError("micro-batcher closed")
            self._tracer.finish_request(pending.rid)
            pending.event.set()
            self._dispose()

    # ------------------------------------------------------------------ #

    def _shed_dead(self, p: _Pending) -> bool:
        """Drop an entry nobody benefits from batching: abandoned
        (waiter gone) or deadline-expired (shed with a typed error
        BEFORE it joins a device batch — the admission-control
        contract). Returns True when the entry was shed."""
        if p.abandoned:
            self._m_abandoned.inc()
            if self._usage is not None:
                self._usage.record_drop(p.tenant, "abandoned")
            self._flight_rec(
                "drop", cause="abandoned", rows=p.rows, tenant=p.tenant,
            )
            self._tracer.finish_request(p.rid)
            self._dispose()
            return True
        if p.deadline is not None and time.perf_counter() > p.deadline:
            waited_ms = (time.perf_counter() - p.submitted) * 1e3
            p.error = DeadlineExceeded(
                f"request deadline expired while queued "
                f"(waited {waited_ms:.0f} ms)",
                deadline_ms=(p.deadline - p.submitted) * 1e3,
            )
            self._m_deadline_shed.inc()
            if self._usage is not None:
                self._usage.record_deadline_shed(p.tenant)
            self._flight_rec(
                "drop", cause="deadline_shed", rows=p.rows,
                tenant=p.tenant, waited_ms=round(waited_ms, 3),
            )
            self._tracer.finish_request(p.rid)
            p.event.set()
            self._dispose()
            return True
        return False

    def _drain(self) -> List[_Pending]:
        while True:  # skip dead entries without starting a batch
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                return []
            if not self._shed_dead(first):
                break
        batch = [first]
        rows = first.rows
        deadline = threading.Event()
        timer = threading.Timer(self.max_wait_s, deadline.set)
        timer.start()
        try:
            while rows < self.max_batch_size and not deadline.is_set():
                try:
                    nxt = self._queue.get(timeout=self.max_wait_s / 4)
                except queue.Empty:
                    continue
                if self._shed_dead(nxt):
                    continue
                if rows + nxt.rows > self.max_batch_size:
                    self._queue.put(nxt)  # over cap: leave for the next batch
                    break
                batch.append(nxt)
                rows += nxt.rows
        finally:
            timer.cancel()
        self._g_queue_depth.set(self._queue.qsize())
        return batch

    def _run(self):
        while not self._stop.is_set():
            batch = self._drain()
            # belt: a submit may time out between drain and dispatch
            still_live = [p for p in batch if not p.abandoned]
            for p in batch:
                if p.abandoned:
                    self._tracer.finish_request(p.rid)
            self._m_abandoned.inc(len(batch) - len(still_live))
            self._dispose(len(batch) - len(still_live))
            batch = still_live
            if not batch:
                continue
            try:
                if self._faults is not None:
                    # chaos point: a raise here surfaces to every waiter
                    # in the batch (the shared-device-call error path)
                    self._faults.fire("batcher.predict")
                t_start = time.perf_counter()
                for p in batch:
                    p.queue_wait_ms = (t_start - p.submitted) * 1e3
                rl = self.row_lists
                feats = _concat([p.features for p in batch], rl)
                total = sum(p.rows for p in batch)
                # a single request may exceed the largest bucket: chunk the
                # device calls so every call still hits a bucketed shape
                cap = self.buckets[-1]
                parts = []
                for start in range(0, total, cap):
                    stop = min(start + cap, total)
                    chunk = (
                        _slice_rows(feats, start, stop, rl) if total > cap else feats
                    )
                    padded = _pad_to(chunk, self._bucket(stop - start), rl)
                    out = self._predict_fn(padded)
                    if not rl and isinstance(out, list):
                        # array mode normalizes plain-list outputs (the
                        # list IS the batch axis) so chunk concat/slice
                        # keep batch semantics
                        out = np.asarray(out)
                    parts.append(_slice_rows(out, 0, stop - start, rl))
                result = _concat(parts, rl) if len(parts) > 1 else parts[0]
                t_end = time.perf_counter()
                device_ms = (t_end - t_start) * 1e3
                for p in batch:
                    # queue → predict, mirroring the engine's span
                    # vocabulary; the shared device call is one span
                    # per entry so each request's tree is self-complete
                    self._tracer.record_span(
                        p.rid, "queue", p.submitted, t_start
                    )
                    self._tracer.record_span(
                        p.rid, "predict", t_start, t_end, rows=p.rows,
                        batch_rows=total,
                    )
                offset = 0
                for p in batch:
                    p.result = _slice_rows(result, offset, offset + p.rows, rl)
                    p.device_ms = device_ms  # the shared batched call
                    offset += p.rows
                self._m_batches.inc()
                self._m_rows.inc(total)
                self._h_batch.observe(total)
                for p in batch:
                    self._h_queue.observe(p.queue_wait_ms)
                    self._h_device.observe(p.device_ms)
                self._m_requests.inc(len(batch))
                if self._usage is not None:
                    # the shared device call split by row share; FLOPs
                    # from the tracker's cost analysis per chunked part
                    shares: dict = {}
                    for p in batch:
                        shares[p.tenant] = shares.get(p.tenant, 0) + p.rows
                    flops = 0.0
                    if self._programs is not None:
                        flops = (
                            self._programs.cost("batcher.predict")[0]
                            * len(parts)
                        )
                    self._usage.attribute(
                        shares, device_s=device_ms / 1e3, flops=flops,
                    )
                    for p in batch:
                        self._usage.finish_request(
                            p.tenant, queue_ms=p.queue_wait_ms,
                        )
                self._flight_rec(
                    "batch", rows=total, entries=len(batch),
                    device_ms=round(device_ms, 3),
                )
            except BaseException as exc:  # surface errors to every waiter
                logger.info(f"micro-batcher error: {exc!r}")
                self._m_errors.inc(len(batch))
                if self._usage is not None:
                    for p in batch:
                        self._usage.record_drop(p.tenant, "error")
                self._flight_rec(
                    "error", entries=len(batch), error=repr(exc)[:200]
                )
                for p in batch:
                    p.error = exc
            finally:
                for p in batch:
                    self._tracer.finish_request(p.rid)
                    p.event.set()
                self._dispose(len(batch))
