"""On-device micro-batcher: coalesce requests, pad to buckets, one call.

No reference counterpart — unionml/fastapi.py:50-64 runs one predictor
call per HTTP request. On TPU that wastes the MXU (batch-1 matmuls) and
pays dispatch latency per request. This batcher:

1. queues concurrent requests,
2. drains up to ``max_batch_size`` of them (waiting at most
   ``max_wait_ms`` after the first arrival),
3. concatenates features along the batch axis and right-pads to the next
   **bucket size** so XLA compiles exactly ``len(buckets)`` executables
   (SURVEY.md §7 hard part (e): bucketed shapes vs. recompilation),
4. runs the predictor once, splits results back per-request.

Thread-based (works under any transport, stdlib or ASGI); the device call
itself is serialized, which is the desired behavior on a single chip.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from unionml_tpu import telemetry
from unionml_tpu._logging import logger

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64)

# batch-size histogram bounds: the row counts are small powers of two
# (bucketed shapes), so the ms buckets would waste resolution
BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def _leading_dim(features: Any, row_lists: bool) -> int:
    if row_lists:
        return len(features)
    import jax

    leaves = jax.tree_util.tree_leaves(features)
    return int(leaves[0].shape[0]) if leaves else 0


def _concat(items: Sequence[Any], row_lists: bool) -> Any:
    if row_lists:
        out: list = []
        for i in items:
            out.extend(i)
        return out
    import jax

    return jax.tree_util.tree_map(lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0), *items)


def _pad_to(features: Any, n: int, row_lists: bool) -> Any:
    if row_lists:
        # replicate the last row; the split below drops padded results
        return list(features) + [features[-1]] * (n - len(features))
    import jax

    def pad(x):
        x = np.asarray(x)
        if x.shape[0] >= n:
            return x
        pad_width = [(0, n - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        return np.pad(x, pad_width, mode="edge")

    return jax.tree_util.tree_map(pad, features)


def _slice_rows(result: Any, start: int, stop: int, row_lists: bool) -> Any:
    if row_lists:
        return list(result)[start:stop]
    import jax

    # array mode: lists/tuples/dicts are pytree STRUCTURE; every leaf
    # slices along its batch axis
    return jax.tree_util.tree_map(lambda x: np.asarray(x)[start:stop], result)


@dataclass
class _Pending:
    features: Any
    rows: int
    event: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None
    submitted: float = 0.0
    queue_wait_ms: float = 0.0
    device_ms: float = 0.0
    # waiter gave up (submit timeout): skip at drain time instead of
    # burning a device call on a result nobody will read (mirrors the
    # engine's req.abandoned convention)
    abandoned: bool = False


class MicroBatcher:
    """Coalesce concurrent predict calls into bucketed device batches."""

    def __init__(
        self,
        predict_fn: Callable[[Any], Any],
        *,
        max_batch_size: int = 64,
        max_wait_ms: float = 5.0,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        row_lists: bool = False,
        registry: Optional[telemetry.MetricsRegistry] = None,
    ):
        """``row_lists=True``: features/results are plain Python lists of
        per-example rows (possibly ragged — LLM token-id prompts), so the
        batcher coalesces by list concat instead of array concat. Use for
        predictors with the make_lm_predictor contract.

        ``registry``: explicit telemetry sink; defaults to the
        process-global registry so ``GET /metrics`` covers this batcher
        (series isolated per instance by the ``batcher`` label)."""
        self._predict_fn = predict_fn
        self.row_lists = row_lists
        self.max_batch_size = max_batch_size
        self.max_wait_s = max_wait_ms / 1000.0
        self.buckets = tuple(sorted(set(buckets) | {max_batch_size}))
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        self._stop = threading.Event()
        self._registry = registry if registry is not None else telemetry.get_registry()
        self.instance = telemetry.instance_label("batcher")
        self._build_instruments()
        self._worker = threading.Thread(target=self._run, daemon=True, name="unionml-tpu-batcher")
        self._worker.start()

    def _build_instruments(self):
        R, lbl = self._registry, {"batcher": self.instance}

        def counter(name, help):
            return R.counter(name, help, ("batcher",)).labels(**lbl)

        self._m_requests = counter(
            "unionml_batcher_requests_total",
            "Requests completed through a batched device call.",
        )
        self._m_errors = counter(
            "unionml_batcher_errors_total",
            "Requests failed by a predictor/batcher error.",
        )
        self._m_abandoned = counter(
            "unionml_batcher_abandoned_total",
            "Requests whose submit() timed out before the batch ran "
            "(skipped at drain time, no device call burned).",
        )
        self._m_batches = counter(
            "unionml_batcher_batches_total", "Batched device calls.",
        )
        self._m_rows = counter(
            "unionml_batcher_batched_rows_total",
            "Rows coalesced into batched device calls.",
        )
        self._h_batch = R.histogram(
            "unionml_batcher_batch_rows",
            "Rows per batched device call (pre-padding).",
            ("batcher",), buckets=BATCH_SIZE_BUCKETS,
        ).labels(**lbl)
        self._h_queue = R.histogram(
            "unionml_batcher_queue_wait_ms",
            "Submit-to-batch-start wait per request.", ("batcher",),
        ).labels(**lbl)
        self._h_device = R.histogram(
            "unionml_batcher_device_ms",
            "Shared batched device-call time per request.", ("batcher",),
        ).labels(**lbl)

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def submit(self, features: Any, timeout: Optional[float] = 60.0) -> Any:
        """Block until the batched prediction for ``features`` is ready.

        A timed-out submit marks its entry **abandoned**: the worker
        skips it at drain time (``batcher_abandoned_total``) instead of
        burning a device call on a result nobody will read."""
        pending = _Pending(
            features=features, rows=_leading_dim(features, self.row_lists),
            submitted=time.perf_counter(),
        )
        self._queue.put(pending)
        if not pending.event.wait(timeout):
            pending.abandoned = True
            raise TimeoutError("micro-batcher did not produce a result in time")
        if pending.error is not None:
            raise pending.error
        return pending.result

    def stats(self) -> dict:
        """Serving observability: queue-wait vs device-time split.

        A thin view over this instance's telemetry-registry series (the
        same numbers ``GET /metrics`` exposes), keeping the historical
        key shape."""
        batches = int(self._m_batches.value)
        out = {
            "engine": "micro-batch",
            "completed_requests": int(self._m_requests.value),
            "batches": batches,
            "mean_batch_rows": round(
                int(self._m_rows.value) / max(1, batches), 2
            ),
        }
        for name, h in (
            ("queue_wait_ms", self._h_queue), ("device_ms", self._h_device)
        ):
            summary = h.summary()
            if summary:
                out[name] = summary
        return out

    def reset_stats(self) -> None:
        """Zero this instance's observability series (benchmarks call
        this between scenarios so each phase's /stats describes only
        that phase); scrapers see the resets as counter restarts."""
        for m in (
            self._m_requests, self._m_errors, self._m_abandoned,
            self._m_batches, self._m_rows, self._h_batch, self._h_queue,
            self._h_device,
        ):
            m.reset()

    def close(self):
        self._stop.set()
        self._worker.join(timeout=1.0)
        # fail fast for requests still queued instead of letting their
        # submit() calls block until timeout
        while True:
            try:
                pending = self._queue.get_nowait()
            except queue.Empty:
                break
            pending.error = RuntimeError("micro-batcher closed")
            pending.event.set()

    # ------------------------------------------------------------------ #

    def _drain(self) -> List[_Pending]:
        while True:  # skip abandoned entries without starting a batch
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                return []
            if not first.abandoned:
                break
            self._m_abandoned.inc()
        batch = [first]
        rows = first.rows
        deadline = threading.Event()
        timer = threading.Timer(self.max_wait_s, deadline.set)
        timer.start()
        try:
            while rows < self.max_batch_size and not deadline.is_set():
                try:
                    nxt = self._queue.get(timeout=self.max_wait_s / 4)
                except queue.Empty:
                    continue
                if nxt.abandoned:
                    self._m_abandoned.inc()
                    continue
                if rows + nxt.rows > self.max_batch_size:
                    self._queue.put(nxt)  # over cap: leave for the next batch
                    break
                batch.append(nxt)
                rows += nxt.rows
        finally:
            timer.cancel()
        return batch

    def _run(self):
        while not self._stop.is_set():
            batch = self._drain()
            # belt: a submit may time out between drain and dispatch
            still_live = [p for p in batch if not p.abandoned]
            self._m_abandoned.inc(len(batch) - len(still_live))
            batch = still_live
            if not batch:
                continue
            try:
                t_start = time.perf_counter()
                for p in batch:
                    p.queue_wait_ms = (t_start - p.submitted) * 1e3
                rl = self.row_lists
                feats = _concat([p.features for p in batch], rl)
                total = sum(p.rows for p in batch)
                # a single request may exceed the largest bucket: chunk the
                # device calls so every call still hits a bucketed shape
                cap = self.buckets[-1]
                parts = []
                for start in range(0, total, cap):
                    stop = min(start + cap, total)
                    chunk = (
                        _slice_rows(feats, start, stop, rl) if total > cap else feats
                    )
                    padded = _pad_to(chunk, self._bucket(stop - start), rl)
                    out = self._predict_fn(padded)
                    if not rl and isinstance(out, list):
                        # array mode normalizes plain-list outputs (the
                        # list IS the batch axis) so chunk concat/slice
                        # keep batch semantics
                        out = np.asarray(out)
                    parts.append(_slice_rows(out, 0, stop - start, rl))
                result = _concat(parts, rl) if len(parts) > 1 else parts[0]
                device_ms = (time.perf_counter() - t_start) * 1e3
                offset = 0
                for p in batch:
                    p.result = _slice_rows(result, offset, offset + p.rows, rl)
                    p.device_ms = device_ms  # the shared batched call
                    offset += p.rows
                self._m_batches.inc()
                self._m_rows.inc(total)
                self._h_batch.observe(total)
                for p in batch:
                    self._h_queue.observe(p.queue_wait_ms)
                    self._h_device.observe(p.device_ms)
                self._m_requests.inc(len(batch))
            except BaseException as exc:  # surface errors to every waiter
                logger.info(f"micro-batcher error: {exc!r}")
                self._m_errors.inc(len(batch))
                for p in batch:
                    p.error = exc
            finally:
                for p in batch:
                    p.event.set()
