"""On-device micro-batcher: coalesce requests, pad to buckets, one call.

No reference counterpart — unionml/fastapi.py:50-64 runs one predictor
call per HTTP request. On TPU that wastes the MXU (batch-1 matmuls) and
pays dispatch latency per request. This batcher:

1. queues concurrent requests,
2. drains up to ``max_batch_size`` of them (waiting at most
   ``max_wait_ms`` after the first arrival),
3. concatenates features along the batch axis and right-pads to the next
   **bucket size** so XLA compiles exactly ``len(buckets)`` executables
   (SURVEY.md §7 hard part (e): bucketed shapes vs. recompilation),
4. runs the predictor once, splits results back per-request.

Thread-based (works under any transport, stdlib or ASGI); the device call
itself is serialized, which is the desired behavior on a single chip.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from unionml_tpu._logging import logger

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


def _leading_dim(features: Any, row_lists: bool) -> int:
    if row_lists:
        return len(features)
    import jax

    leaves = jax.tree_util.tree_leaves(features)
    return int(leaves[0].shape[0]) if leaves else 0


def _concat(items: Sequence[Any], row_lists: bool) -> Any:
    if row_lists:
        out: list = []
        for i in items:
            out.extend(i)
        return out
    import jax

    return jax.tree_util.tree_map(lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0), *items)


def _pad_to(features: Any, n: int, row_lists: bool) -> Any:
    if row_lists:
        # replicate the last row; the split below drops padded results
        return list(features) + [features[-1]] * (n - len(features))
    import jax

    def pad(x):
        x = np.asarray(x)
        if x.shape[0] >= n:
            return x
        pad_width = [(0, n - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        return np.pad(x, pad_width, mode="edge")

    return jax.tree_util.tree_map(pad, features)


def _slice_rows(result: Any, start: int, stop: int, row_lists: bool) -> Any:
    if row_lists:
        return list(result)[start:stop]
    import jax

    # array mode: lists/tuples/dicts are pytree STRUCTURE; every leaf
    # slices along its batch axis
    return jax.tree_util.tree_map(lambda x: np.asarray(x)[start:stop], result)


@dataclass
class _Pending:
    features: Any
    rows: int
    event: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None
    submitted: float = 0.0
    queue_wait_ms: float = 0.0
    device_ms: float = 0.0


class MicroBatcher:
    """Coalesce concurrent predict calls into bucketed device batches."""

    def __init__(
        self,
        predict_fn: Callable[[Any], Any],
        *,
        max_batch_size: int = 64,
        max_wait_ms: float = 5.0,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        row_lists: bool = False,
    ):
        """``row_lists=True``: features/results are plain Python lists of
        per-example rows (possibly ragged — LLM token-id prompts), so the
        batcher coalesces by list concat instead of array concat. Use for
        predictors with the make_lm_predictor contract."""
        self._predict_fn = predict_fn
        self.row_lists = row_lists
        self.max_batch_size = max_batch_size
        self.max_wait_s = max_wait_ms / 1000.0
        self.buckets = tuple(sorted(set(buckets) | {max_batch_size}))
        self._queue: "queue.Queue[_Pending]" = queue.Queue()
        self._stop = threading.Event()
        self._stats_lock = threading.Lock()
        # (queue_wait_ms, device_ms) floats only — archiving _Pending
        # objects would pin every request's features/result payloads
        self._done: List[Tuple[float, float]] = []
        self._done_total = 0
        self._batches = 0
        self._batched_rows = 0
        self._worker = threading.Thread(target=self._run, daemon=True, name="unionml-tpu-batcher")
        self._worker.start()

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def submit(self, features: Any, timeout: Optional[float] = 60.0) -> Any:
        """Block until the batched prediction for ``features`` is ready."""
        pending = _Pending(
            features=features, rows=_leading_dim(features, self.row_lists),
            submitted=time.perf_counter(),
        )
        self._queue.put(pending)
        if not pending.event.wait(timeout):
            raise TimeoutError("micro-batcher did not produce a result in time")
        if pending.error is not None:
            raise pending.error
        return pending.result

    def stats(self) -> dict:
        """Serving observability: queue-wait vs device-time split."""
        from unionml_tpu.serving._stats import percentile_summary

        with self._stats_lock:
            done = list(self._done)
            total = self._done_total
            batches, rows = self._batches, self._batched_rows
        out = {
            "engine": "micro-batch",
            "completed_requests": total,
            "batches": batches,
            "mean_batch_rows": round(rows / max(1, batches), 2),
        }
        if done:
            for i, name in enumerate(("queue_wait_ms", "device_ms")):
                out[name] = percentile_summary([rec[i] for rec in done])
        return out

    def reset_stats(self) -> None:
        """Zero the observability aggregates (benchmarks call this between
        scenarios so each phase's /stats describes only that phase)."""
        with self._stats_lock:
            self._done.clear()
            self._done_total = 0
            self._batches = 0
            self._batched_rows = 0

    def close(self):
        self._stop.set()
        self._worker.join(timeout=1.0)
        # fail fast for requests still queued instead of letting their
        # submit() calls block until timeout
        while True:
            try:
                pending = self._queue.get_nowait()
            except queue.Empty:
                break
            pending.error = RuntimeError("micro-batcher closed")
            pending.event.set()

    # ------------------------------------------------------------------ #

    def _drain(self) -> List[_Pending]:
        try:
            first = self._queue.get(timeout=0.1)
        except queue.Empty:
            return []
        batch = [first]
        rows = first.rows
        deadline = threading.Event()
        timer = threading.Timer(self.max_wait_s, deadline.set)
        timer.start()
        try:
            while rows < self.max_batch_size and not deadline.is_set():
                try:
                    nxt = self._queue.get(timeout=self.max_wait_s / 4)
                except queue.Empty:
                    continue
                if rows + nxt.rows > self.max_batch_size:
                    self._queue.put(nxt)  # over cap: leave for the next batch
                    break
                batch.append(nxt)
                rows += nxt.rows
        finally:
            timer.cancel()
        return batch

    def _run(self):
        while not self._stop.is_set():
            batch = self._drain()
            if not batch:
                continue
            try:
                t_start = time.perf_counter()
                for p in batch:
                    p.queue_wait_ms = (t_start - p.submitted) * 1e3
                rl = self.row_lists
                feats = _concat([p.features for p in batch], rl)
                total = sum(p.rows for p in batch)
                # a single request may exceed the largest bucket: chunk the
                # device calls so every call still hits a bucketed shape
                cap = self.buckets[-1]
                parts = []
                for start in range(0, total, cap):
                    stop = min(start + cap, total)
                    chunk = (
                        _slice_rows(feats, start, stop, rl) if total > cap else feats
                    )
                    padded = _pad_to(chunk, self._bucket(stop - start), rl)
                    out = self._predict_fn(padded)
                    if not rl and isinstance(out, list):
                        # array mode normalizes plain-list outputs (the
                        # list IS the batch axis) so chunk concat/slice
                        # keep batch semantics
                        out = np.asarray(out)
                    parts.append(_slice_rows(out, 0, stop - start, rl))
                result = _concat(parts, rl) if len(parts) > 1 else parts[0]
                device_ms = (time.perf_counter() - t_start) * 1e3
                offset = 0
                for p in batch:
                    p.result = _slice_rows(result, offset, offset + p.rows, rl)
                    p.device_ms = device_ms  # the shared batched call
                    offset += p.rows
                with self._stats_lock:
                    self._batches += 1
                    self._batched_rows += total
                    self._done.extend(
                        (p.queue_wait_ms, p.device_ms) for p in batch
                    )
                    self._done_total += len(batch)
                    if len(self._done) > 10_000:
                        del self._done[:5_000]
            except BaseException as exc:  # surface errors to every waiter
                logger.info(f"micro-batcher error: {exc!r}")
                for p in batch:
                    p.error = exc
            finally:
                for p in batch:
                    p.event.set()
