"""Compat shim: :func:`percentile_summary` moved to
:mod:`unionml_tpu.telemetry` (diagnostics and the program-introspection
registry need it too, and telemetry is the layer everything already
imports). Serving-internal and benchmark imports keep working through
this re-export."""

from __future__ import annotations

from unionml_tpu.telemetry import percentile_summary

__all__ = ["percentile_summary"]
