"""Shared percentile summary for serving observability surfaces."""

from __future__ import annotations

import math
from typing import Sequence


def percentile_summary(values: Sequence[float]) -> dict:
    """p50/p95/p99/mean/n of a non-empty sample.

    Percentiles use nearest-rank ``ceil(q * n) - 1`` (the formula the
    benchmarks share through this helper): for small windows
    ``int(q * n)`` indexes the sample MAXIMUM — one cold-compile outlier
    would be reported as the p95 and misdirect tail-latency attribution.
    ``n`` is the sample count, so a consumer can tell a p99 computed
    over 3 requests from one computed over 10k.
    """
    vals = sorted(values)
    n = len(vals)
    return {
        "p50": round(vals[n // 2], 1),
        "p95": round(vals[max(0, math.ceil(0.95 * n) - 1)], 1),
        "p99": round(vals[max(0, math.ceil(0.99 * n) - 1)], 1),
        "mean": round(sum(vals) / n, 1),
        "n": n,
    }
