"""Shared percentile summary for serving observability surfaces."""

from __future__ import annotations

import math
from typing import Sequence


def percentile_summary(values: Sequence[float]) -> dict:
    """p50/p95/mean of a non-empty sample.

    p95 uses ``ceil(0.95 * n) - 1`` (the same formula as
    ``benchmarks/serve_http.py``): for small windows ``int(0.95 * n)``
    indexes the sample MAXIMUM — one cold-compile outlier would be
    reported as the p95 and misdirect tail-latency attribution.
    """
    vals = sorted(values)
    n = len(vals)
    return {
        "p50": round(vals[n // 2], 1),
        "p95": round(vals[max(0, math.ceil(0.95 * n) - 1)], 1),
        "mean": round(sum(vals) / n, 1),
    }
