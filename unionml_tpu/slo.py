"""SLO watchdog: objectives evaluated as multi-window burn rates.

The telemetry layer records what happened; nothing in the system
*judges* it — a TTFT regression or an error burst is visible on a
dashboard but never changes ``GET /health``, so load balancers keep
routing to a replica that is missing its objectives. This module closes
that loop, Google-SRE style (multi-window, multi-burn-rate alerting):

- apps declare **objectives** against the live
  :class:`~unionml_tpu.telemetry.MetricsRegistry` series —
  :class:`LatencyObjective` (a latency percentile bound, e.g. engine
  TTFT p95 ≤ 250 ms, read from a histogram's bucket counts),
  :class:`AvailabilityObjective` (good-fraction ≥ target, e.g. HTTP
  availability ≥ 99.9% from the error/request counters), and
  :class:`GaugeObjective` (a level bound, e.g. decode MFU ≥ 0.2);
- the :class:`SloWatchdog` samples the registry on every
  :meth:`~SloWatchdog.evaluate` (the transports call it from
  ``GET /health`` and ``GET /debug/slo``, so the health-probe cadence
  IS the sampling cadence) and computes each objective's **burn rate**
  — error-budget consumption speed, ``bad_fraction / (1 - target)`` —
  over a **fast** window (default 5 min; catches a cliff) and a
  **slow** window (default 1 h; ignores blips). An objective breaches
  when BOTH windows burn past their thresholds, and clears as soon as
  the fast window runs clean — fast to fire, fast to recover, immune
  to a single slow request;
- breaches publish ``unionml_slo_burn_rate{objective,window}`` /
  ``unionml_slo_breached{objective}`` /
  ``unionml_slo_breaches_total{objective}`` into the registry, surface
  in ``GET /debug/slo``, and flip
  :meth:`~unionml_tpu.serving.http.ServingApp.health` to ``degraded``
  (→ HTTP 503) — so the PR-3 admission/breaker machinery and the load
  balancer react to *objective burn*, not just crash loops
  (docs/observability.md).

Everything here is stdlib-only, thread-safe, and deterministic:
``evaluate(now=...)`` takes an explicit clock so the burn-rate window
math is unit-testable without sleeping.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from unionml_tpu import telemetry

__all__ = [
    "AvailabilityObjective",
    "DEFAULT_FAST_WINDOW_S",
    "DEFAULT_SLOW_WINDOW_S",
    "GaugeObjective",
    "LatencyObjective",
    "SloWatchdog",
]

DEFAULT_FAST_WINDOW_S = 300.0     # 5 min: the page-now window
DEFAULT_SLOW_WINDOW_S = 3600.0    # 1 h: the is-it-sustained window

# Google SRE workbook pairing for a 5m/1h multiwindow alert: the fast
# window must burn hard (14.4x eats a 30-day budget in ~2 days) AND the
# slow window must confirm it is not a blip (6x sustained over an hour)
DEFAULT_FAST_BURN = 14.4
DEFAULT_SLOW_BURN = 6.0


def _match(
    labelnames: Sequence[str],
    values: Sequence[str],
    label_filter: Optional[Dict[str, str]],
) -> bool:
    if not label_filter:
        return True
    pairs = dict(zip(labelnames, values))
    return all(pairs.get(k) == str(v) for k, v in label_filter.items())


class _Objective:
    """Shared declaration shape: a name, burn thresholds, and the
    registry families the watchdog must snapshot for it."""

    kind = "objective"

    def __init__(self, name: str, fast_burn: float, slow_burn: float):
        self.name = str(name)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)

    def metric_names(self) -> Tuple[str, ...]:
        raise NotImplementedError

    def evaluate_window(
        self,
        baseline: Optional[dict],
        samples: List[Tuple[float, dict]],
    ) -> dict:
        """Burn over one window: ``baseline`` is the newest snapshot at
        or before the window start (None when history is younger than
        the window), ``samples`` the in-window snapshots oldest→newest
        (current last). Returns ``{"burn_rate": float, ...detail}``."""
        raise NotImplementedError

    def describe(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "fast_burn_threshold": self.fast_burn,
            "slow_burn_threshold": self.slow_burn,
        }


class LatencyObjective(_Objective):
    """``p(target)`` of ``histogram`` ≤ ``threshold_ms``: at most
    ``1 - target`` of the window's observations may exceed the
    threshold; burn rate is the over-threshold fraction divided by
    that budget.

    The threshold is evaluated against the histogram's bucket bounds
    (observations above the smallest bound ≥ ``threshold_ms`` count as
    bad — pick a threshold on a bucket edge, e.g. from
    :data:`telemetry.DEFAULT_MS_BUCKETS`, for exact accounting).
    ``label_filter`` narrows to matching children (e.g.
    ``{"engine": "engine-0"}``); default sums every child. Windows
    with fewer than ``min_events`` observations burn 0 — no traffic is
    not a breach."""

    kind = "latency"

    def __init__(
        self,
        name: str,
        histogram: str,
        threshold_ms: float,
        *,
        target: float = 0.95,
        label_filter: Optional[Dict[str, str]] = None,
        min_events: int = 1,
        fast_burn: float = DEFAULT_FAST_BURN,
        slow_burn: float = DEFAULT_SLOW_BURN,
    ):
        if not 0.0 < target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {target}")
        super().__init__(name, fast_burn, slow_burn)
        self.histogram = str(histogram)
        self.threshold_ms = float(threshold_ms)
        self.target = float(target)
        self.label_filter = dict(label_filter or {})
        self.min_events = int(min_events)

    def metric_names(self) -> Tuple[str, ...]:
        return (self.histogram,)

    def _totals(self, snap: Optional[dict]) -> Tuple[float, float]:
        """(observations, over-threshold observations) summed over the
        matching children of one snapshot."""
        if snap is None:
            return 0.0, 0.0
        fam = snap.get(self.histogram)
        if fam is None or fam["kind"] != "histogram":
            return 0.0, 0.0
        total = bad = 0.0
        for values, payload in fam["children"].items():
            if not _match(fam["labelnames"], values, self.label_filter):
                continue
            bounds, cum = payload["bounds"], payload["cum_counts"]
            count = cum[-1]
            idx = bisect.bisect_left(bounds, self.threshold_ms)
            good = cum[idx] if idx < len(cum) else count
            total += count
            bad += count - good
        return total, bad

    def evaluate_window(self, baseline, samples):
        cur = samples[-1][1] if samples else baseline
        total0, bad0 = self._totals(baseline if baseline is not None
                                    else (samples[0][1] if samples else None))
        total1, bad1 = self._totals(cur)
        events = max(0.0, total1 - total0)
        bad = max(0.0, bad1 - bad0)
        budget = 1.0 - self.target
        fraction = (bad / events) if events >= self.min_events else 0.0
        return {
            "burn_rate": fraction / budget,
            "events": events,
            "bad_events": bad,
            "bad_fraction": round(fraction, 6),
        }

    def describe(self) -> dict:
        return {
            **super().describe(),
            "histogram": self.histogram,
            "threshold_ms": self.threshold_ms,
            "target": self.target,
            "label_filter": self.label_filter,
        }


class AvailabilityObjective(_Objective):
    """Good-fraction ≥ ``target`` (e.g. 0.999): the window's error rate
    — delta of ``errors`` over delta of ``total`` — divided by the
    ``1 - target`` budget is the burn rate. Counters may live in
    different families with different label schemas
    (``unionml_http_errors_total`` vs ``unionml_http_requests_total``);
    each gets its own optional label filter."""

    kind = "availability"

    def __init__(
        self,
        name: str,
        *,
        total: str,
        errors: str,
        target: float = 0.999,
        total_filter: Optional[Dict[str, str]] = None,
        error_filter: Optional[Dict[str, str]] = None,
        min_events: int = 1,
        fast_burn: float = DEFAULT_FAST_BURN,
        slow_burn: float = DEFAULT_SLOW_BURN,
    ):
        if not 0.0 < target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {target}")
        super().__init__(name, fast_burn, slow_burn)
        self.total = str(total)
        self.errors = str(errors)
        self.target = float(target)
        self.total_filter = dict(total_filter or {})
        self.error_filter = dict(error_filter or {})
        self.min_events = int(min_events)

    def metric_names(self) -> Tuple[str, ...]:
        return (self.total, self.errors)

    @staticmethod
    def _sum(snap: Optional[dict], family: str, label_filter) -> float:
        if snap is None:
            return 0.0
        fam = snap.get(family)
        if fam is None or fam["kind"] == "histogram":
            return 0.0
        return sum(
            payload for values, payload in fam["children"].items()
            if _match(fam["labelnames"], values, label_filter)
        )

    def evaluate_window(self, baseline, samples):
        cur = samples[-1][1] if samples else baseline
        base = baseline if baseline is not None else (
            samples[0][1] if samples else None
        )
        total = max(
            0.0,
            self._sum(cur, self.total, self.total_filter)
            - self._sum(base, self.total, self.total_filter),
        )
        errors = max(
            0.0,
            self._sum(cur, self.errors, self.error_filter)
            - self._sum(base, self.errors, self.error_filter),
        )
        budget = 1.0 - self.target
        fraction = (errors / total) if total >= self.min_events else 0.0
        return {
            "burn_rate": fraction / budget,
            "events": total,
            "bad_events": errors,
            "bad_fraction": round(fraction, 6),
        }

    def describe(self) -> dict:
        return {
            **super().describe(),
            "total": self.total,
            "errors": self.errors,
            "target": self.target,
        }


class GaugeObjective(_Objective):
    """A level bound on a gauge (e.g. ``unionml_program_mfu_ratio``
    with ``{"program": "engine.decode"}`` ≥ 0.2): the window value is
    the MEAN of the sampled gauge across the window, and the burn rate
    is 1.0 while the bound is violated, else 0.0 — so with the default
    thresholds (1.0/1.0) a breach requires the violation to hold
    across BOTH windows. Windows with no samples (or, when
    ``skip_zero`` is set, only zero samples — gauges report 0 before
    their source first resolves) burn 0."""

    kind = "gauge"

    def __init__(
        self,
        name: str,
        gauge: str,
        *,
        min_value: Optional[float] = None,
        max_value: Optional[float] = None,
        label_filter: Optional[Dict[str, str]] = None,
        skip_zero: bool = True,
        fast_burn: float = 1.0,
        slow_burn: float = 1.0,
    ):
        if (min_value is None) == (max_value is None):
            raise ValueError("set exactly one of min_value / max_value")
        super().__init__(name, fast_burn, slow_burn)
        self.gauge = str(gauge)
        self.min_value = min_value
        self.max_value = max_value
        self.label_filter = dict(label_filter or {})
        self.skip_zero = bool(skip_zero)

    def metric_names(self) -> Tuple[str, ...]:
        return (self.gauge,)

    def evaluate_window(self, baseline, samples):
        points: List[float] = []
        for _, snap in samples:
            fam = snap.get(self.gauge)
            if fam is None or fam["kind"] == "histogram":
                continue
            vals = [
                payload for values, payload in fam["children"].items()
                if _match(fam["labelnames"], values, self.label_filter)
            ]
            if vals:
                points.append(sum(vals) / len(vals))
        if self.skip_zero:
            points = [p for p in points if p != 0.0]
        if not points:
            return {"burn_rate": 0.0, "value": None}
        value = sum(points) / len(points)
        violated = (
            (self.min_value is not None and value < self.min_value)
            or (self.max_value is not None and value > self.max_value)
        )
        return {"burn_rate": 1.0 if violated else 0.0,
                "value": round(value, 6)}

    def describe(self) -> dict:
        return {
            **super().describe(),
            "gauge": self.gauge,
            "min_value": self.min_value,
            "max_value": self.max_value,
            "label_filter": self.label_filter,
        }


class SloWatchdog:
    """Evaluates declared objectives over fast/slow burn windows against
    a live registry, publishes the ``unionml_slo_*`` series, and
    answers the ``degraded``-or-not question ``health()`` asks.

    Each :meth:`evaluate` snapshots exactly the metric families the
    objectives reference, appends the sample to a bounded history, and
    computes per-objective burn rates over the **fast** and **slow**
    windows (a window's baseline is the newest sample at or before its
    start, so counter deltas cover the whole window once history is
    deep enough). Evaluation is cheap (one registry read + arithmetic)
    and thread-safe — the transports call it from ``GET /health``, so
    the probe cadence is the sampling cadence; call
    :meth:`start`/:meth:`stop` for a background ticker where probes
    are sparse."""

    def __init__(
        self,
        objectives: Sequence[_Objective] = (),
        *,
        registry: Optional["telemetry.MetricsRegistry"] = None,
        fast_window_s: float = DEFAULT_FAST_WINDOW_S,
        slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
        min_sample_gap_s: float = 0.0,
        max_samples: int = 7200,
    ):
        if fast_window_s <= 0 or slow_window_s <= 0:
            raise ValueError("windows must be positive")
        if slow_window_s < fast_window_s:
            raise ValueError(
                f"slow window {slow_window_s}s shorter than fast "
                f"{fast_window_s}s"
            )
        self.objectives: List[_Objective] = []
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.min_sample_gap_s = float(min_sample_gap_s)
        self.max_samples = int(max_samples)
        self._registry = (
            registry if registry is not None else telemetry.get_registry()
        )
        self._lock = threading.Lock()
        self._history: "deque[Tuple[float, dict]]" = deque()
        self._breached: Dict[str, bool] = {}
        self._last_report: Optional[dict] = None
        self._ticker: Optional[threading.Thread] = None
        self._ticker_stop = threading.Event()
        R = self._registry
        self._g_burn = R.gauge(
            "unionml_slo_burn_rate",
            "Error-budget burn rate per objective and window (1.0 = "
            "burning exactly at budget).",
            ("objective", "window"),
        )
        self._g_breached = R.gauge(
            "unionml_slo_breached",
            "1 while the objective is breached (both windows past "
            "their burn thresholds).",
            ("objective",),
        )
        self._m_breaches = R.counter(
            "unionml_slo_breaches_total",
            "ok -> breached transitions per objective.",
            ("objective",),
        )
        for obj in objectives:
            self.add_objective(obj)

    def add_objective(self, objective: _Objective) -> None:
        if any(o.name == objective.name for o in self.objectives):
            raise ValueError(f"duplicate objective name {objective.name!r}")
        self.objectives.append(objective)
        # the series exist from declaration time, not first breach — a
        # dashboard can alert on absence vs. a healthy 0
        self._g_breached.labels(objective.name).set(0.0)
        for window in ("fast", "slow"):
            self._g_burn.labels(objective.name, window).set(0.0)

    # -- sampling ----------------------------------------------------------

    def _snapshot(self) -> dict:
        """Point-in-time values of every family the objectives read:
        ``{name: {"kind", "labelnames", "children": {values: payload}}}``
        where payload is a float (counter/gauge) or bucket detail
        (histogram)."""
        wanted = set()
        for obj in self.objectives:
            wanted.update(obj.metric_names())
        snap: dict = {}
        for family in self._registry.collect():
            if family.name not in wanted:
                continue
            children: dict = {}
            for values, child in family.children():
                if family.kind == "histogram":
                    buckets = child.buckets()
                    children[values] = {
                        "bounds": [b for b, _ in buckets[:-1]],
                        "cum_counts": [c for _, c in buckets],
                    }
                else:
                    children[values] = float(child.value)
            snap[family.name] = {
                "kind": family.kind,
                "labelnames": family.labelnames,
                "children": children,
            }
        return snap

    def _window(
        self, now: float, window_s: float
    ) -> Tuple[Optional[dict], List[Tuple[float, dict]]]:
        """(baseline snapshot, in-window samples oldest→newest) — call
        with the lock held, after the current sample was appended."""
        start = now - window_s
        baseline = None
        samples: List[Tuple[float, dict]] = []
        for t, snap in self._history:
            if t <= start:
                baseline = snap
            else:
                samples.append((t, snap))
        return baseline, samples

    # -- evaluation --------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> dict:
        """Sample the registry, recompute every objective's fast/slow
        burn rates, publish the ``unionml_slo_*`` series, and return
        the ``GET /debug/slo`` report. ``now`` (monotonic seconds)
        exists for deterministic tests; production passes nothing."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            if (
                self._history
                and self.min_sample_gap_s > 0.0
                and now - self._history[-1][0] < self.min_sample_gap_s
                and self._last_report is not None
            ):
                return self._last_report
            self._history.append((now, self._snapshot()))
            horizon = now - self.slow_window_s
            while len(self._history) > 1 and (
                self._history[1][0] <= horizon
                or len(self._history) > self.max_samples
            ):
                # keep one sample at/before the horizon as the slow
                # window's baseline
                self._history.popleft()
            report_objs = []
            breached_names = []
            for obj in self.objectives:
                windows = {}
                for window, window_s in (
                    ("fast", self.fast_window_s),
                    ("slow", self.slow_window_s),
                ):
                    baseline, samples = self._window(now, window_s)
                    detail = obj.evaluate_window(baseline, samples)
                    detail["window_s"] = window_s
                    detail["burn_rate"] = round(detail["burn_rate"], 4)
                    windows[window] = detail
                    self._g_burn.labels(obj.name, window).set(
                        detail["burn_rate"]
                    )
                breached = (
                    windows["fast"]["burn_rate"] >= obj.fast_burn
                    and windows["slow"]["burn_rate"] >= obj.slow_burn
                )
                was = self._breached.get(obj.name, False)
                if breached and not was:
                    self._m_breaches.labels(obj.name).inc()
                self._breached[obj.name] = breached
                self._g_breached.labels(obj.name).set(1.0 if breached else 0.0)
                if breached:
                    breached_names.append(obj.name)
                report_objs.append({
                    **obj.describe(),
                    "windows": windows,
                    "breached": breached,
                })
            self._last_report = {
                "objectives": report_objs,
                "breached": breached_names,
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
                "samples": len(self._history),
            }
            return self._last_report

    def breached(self) -> List[str]:
        """Objectives breached as of the LAST evaluation (no sampling;
        ``health()`` calls :meth:`evaluate` which refreshes this)."""
        with self._lock:
            return [n for n, b in self._breached.items() if b]

    def health_status(self) -> str:
        """``"degraded"`` while any objective is breached, else
        ``"ok"`` — the contribution ``ServingApp.health`` merges."""
        return "degraded" if self.breached() else "ok"

    def burn_score(self, window: str = "fast") -> float:
        """Max burn rate across objectives over ``window`` (``"fast"``
        or ``"slow"``) as of the LAST evaluation (0.0 before any) — a
        scalar load-shifting signal: the fleet router deprioritizes
        replicas whose objectives are burning budget even before they
        formally breach, so traffic shifts ahead of the page, not
        after it. The autoscaler reads BOTH windows (scale-out needs
        the fast window to burn hard AND the slow window to confirm
        it is sustained — the same multiwindow discipline as paging).
        No sampling happens here; the health-probe cadence (which
        calls :meth:`evaluate`) is the refresh cadence."""
        if window not in ("fast", "slow"):
            raise ValueError(
                f"window must be 'fast' or 'slow', got {window!r}"
            )
        with self._lock:
            if self._last_report is None:
                return 0.0
            return max(
                (
                    obj["windows"][window]["burn_rate"]
                    for obj in self._last_report["objectives"]
                ),
                default=0.0,
            )

    def burn_scores(self) -> Dict[str, float]:
        """``{"fast": ..., "slow": ...}`` — both windows' max burn
        rates in one read (the autoscaler's sustained-burn signal)."""
        return {
            "fast": self.burn_score("fast"),
            "slow": self.burn_score("slow"),
        }

    # -- optional background ticker ---------------------------------------

    def start(self, interval_s: float = 15.0) -> None:
        """Evaluate every ``interval_s`` on a daemon thread — for
        deployments whose health probes are too sparse to double as
        the sampling cadence. Idempotent."""
        if self._ticker is not None and self._ticker.is_alive():
            return
        self._ticker_stop.clear()

        def tick():
            while not self._ticker_stop.wait(interval_s):
                try:
                    self.evaluate()
                except Exception:
                    pass  # a watchdog bug must never take serving down

        self._ticker = threading.Thread(
            target=tick, daemon=True, name="unionml-tpu-slo-watchdog"
        )
        self._ticker.start()

    def stop(self) -> None:
        self._ticker_stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=2.0)
            self._ticker = None
